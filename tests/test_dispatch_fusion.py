"""Fused multi-step dispatch (-steps_per_dispatch, PR 2).

K prepared minibatches stack into ONE h2d transfer and ONE jitted
lax.scan running all K optimizer steps with donated state (ops.scan,
io.prefetch.MegabatchStager, LearnerBase._dispatch_mega). The contract
these tests pin:

- K>1 runs the SAME per-step core the K=1 path jits, on the SAME batches
  in the SAME order -> the per-step loss trajectory and the final model
  state are identical (`_trace_losses` records both paths' per-step loss
  sums without changing dispatch).
- Ragged tails (last window < K), kind changes (unit-valued vs
  real-valued batches mid-stream) and foreign batch kinds flush to the
  K=1 path one batch at a time — every batch trains exactly once either
  way.
- Donated scan carries never leave stale buffers behind: interleaving
  save_bundle/model_rows with further fused fits equals an uninterrupted
  run.
- The scan body compiles under GSPMD: -steps_per_dispatch with -mesh
  matches the K=1 mesh trajectory (the driver's dryrun_multichip checks
  the same on its virtual mesh).
"""

import numpy as np
import pytest

from hivemall_tpu.io.sparse import (MegaBatch, PackedMegaBatch, SparseBatch,
                                    SparseDataset)
from hivemall_tpu.models.fm import FFMTrainer, FMTrainer
from hivemall_tpu.models.linear import GeneralClassifier


def _linear_ds(n=2200, L=8, dims=1 << 12, seed=0, unit=True):
    rng = np.random.default_rng(seed)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    val = (np.ones(n * L, np.float32) if unit
           else rng.uniform(0.5, 1.5, n * L).astype(np.float32))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    return SparseDataset(idx.ravel(), np.arange(0, n * L + 1, L),
                         val, lab)


def _ffm_ds(n=1500, L=8, dims=1 << 12, F=8, seed=1, unit=True):
    rng = np.random.default_rng(seed)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
    val = (np.ones(n * L, np.float32) if unit
           else rng.uniform(0.5, 1.5, n * L).astype(np.float32))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    return SparseDataset(idx.ravel(), np.arange(0, n * L + 1, L),
                         val, lab, fld.ravel())


def _trajectory(make, ds, k, *, prefetch=False, epochs=1):
    t = make(k)
    t._trace_losses = []
    t.fit(ds, epochs=epochs, shuffle=True, prefetch=prefetch)
    return np.asarray(t._trace_losses), t


def _assert_same_trajectory(make, ds, k=4, *, prefetch=False, epochs=1):
    l1, t1 = _trajectory(make, ds, 1, prefetch=prefetch, epochs=epochs)
    lk, tk = _trajectory(make, ds, k, prefetch=prefetch, epochs=epochs)
    assert len(l1) == len(lk) > 0
    np.testing.assert_allclose(lk, l1, rtol=1e-6, atol=1e-8)
    assert tk._examples == t1._examples
    assert tk._t == t1._t
    return t1, tk


# --- trajectory equality: every dispatch kind -------------------------------

def test_linear_k4_matches_k1_unit_and_real():
    """K>1 == K=1 on a shuffled epoch, with a ragged tail (2200 rows =
    8 full 256-row batches + tail; K=4 -> 2 megabatches + 1 single),
    for BOTH the unit-valued (val=None elision) and real-valued kinds."""
    for unit in (True, False):
        ds = _linear_ds(unit=unit)
        t1, tk = _assert_same_trajectory(
            lambda k: GeneralClassifier(
                f"-dims {1 << 12} -mini_batch 256 -opt adagrad "
                f"-steps_per_dispatch {k}"), ds)
        np.testing.assert_allclose(np.asarray(tk.w), np.asarray(t1.w),
                                   rtol=1e-6, atol=1e-8)
        st = tk.pipeline_stats.as_dict()
        assert st["steps_per_dispatch"] == 4
        assert st["megabatches_staged"] == 2
        assert st["singles_flushed"] == 1


def test_fm_fused_k4_matches_k1():
    for unit in (True, False):
        ds = _linear_ds(n=1100, unit=unit, seed=3)
        _assert_same_trajectory(
            lambda k: FMTrainer(
                f"-dims {1 << 12} -factors 4 -mini_batch 256 -opt adagrad "
                f"-classification -steps_per_dispatch {k}"), ds)


def test_ffm_fieldmajor_and_packed_k4_match_k1():
    """The flagship joint-layout kinds: canonical field-major megabatches
    and (with -pack_input on) PackedMegaBatch — one stacked uint8 buffer
    per 4 steps, unpacked per scan iteration on device."""
    ds = _ffm_ds()
    for extra in ("", "-pack_input on"):
        t1, tk = _assert_same_trajectory(
            lambda k: FFMTrainer(
                f"-dims {1 << 12} -factors 4 -fields 8 -mini_batch 256 "
                f"-opt adagrad -classification -steps_per_dispatch {k} "
                f"{extra}"), ds)
        np.testing.assert_allclose(
            np.asarray(tk.params["T"], np.float32),
            np.asarray(t1.params["T"], np.float32), rtol=1e-6, atol=1e-8)


def test_ffm_pairs_k4_matches_k1():
    """Dense layout (non-pow2 dims) runs the general pairs core — field
    arrays ride the megabatch as a scanned [K, B, L] input."""
    rng = np.random.default_rng(5)
    n, L, dims, F = 1100, 8, 5000, 8
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = rng.integers(0, F, (n, L)).astype(np.int32)
    val = rng.uniform(0.5, 1.5, n * L).astype(np.float32)
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    ds = SparseDataset(idx.ravel(), np.arange(0, n * L + 1, L), val, lab,
                       fld.ravel())
    make = lambda k: FFMTrainer(
        f"-dims {dims} -factors 3 -fields {F} -mini_batch 256 "
        f"-opt adagrad -classification -steps_per_dispatch {k}")
    assert make(1).layout == "dense"
    _assert_same_trajectory(make, ds)


def test_k4_matches_k1_through_prefetcher():
    """The production stack: stager consumed by the DevicePrefetcher
    worker thread (megabatch stage_batch blocks on transfer — the
    staging-ring contract)."""
    ds = _linear_ds(n=1300, seed=7)
    _assert_same_trajectory(
        lambda k: GeneralClassifier(
            f"-dims {1 << 12} -mini_batch 256 -opt adagrad "
            f"-steps_per_dispatch {k}"), ds, prefetch=True)


def test_multi_epoch_shuffled_k4_matches_k1():
    ds = _linear_ds(n=1000, seed=9)
    _assert_same_trajectory(
        lambda k: GeneralClassifier(
            f"-dims {1 << 12} -mini_batch 256 -opt sgd "
            f"-steps_per_dispatch {k}"), ds, epochs=3)


# --- stager mechanics -------------------------------------------------------

def _mk_batch(rng, B=64, L=4, unit=True, n_valid=None):
    idx = rng.integers(1, 1000, (B, L)).astype(np.int32)
    val = None if unit else rng.uniform(0.5, 1.5, (B, L)).astype(np.float32)
    lab = (rng.integers(0, 2, B) * 2 - 1).astype(np.float32)
    return SparseBatch(idx, val, lab, n_valid=n_valid)


def test_stager_kind_change_and_ragged_flush():
    """A real-valued batch arriving mid-window flushes the unit window to
    the K=1 path instead of poisoning it; stream end flushes the ragged
    tail; counts preserve every batch exactly once, in order."""
    from hivemall_tpu.io.prefetch import MegabatchStager
    rng = np.random.default_rng(11)
    batches = ([_mk_batch(rng) for _ in range(3)]          # 3 unit
               + [_mk_batch(rng, unit=False)]              # kind change
               + [_mk_batch(rng) for _ in range(9)]        # 2 windows + tail
               + [_mk_batch(rng, n_valid=17)])             # ragged shape-mate
    out = list(MegabatchStager(iter(batches), 4))
    singles = [o for o in out if isinstance(o, SparseBatch)]
    megas = [o for o in out if isinstance(o, MegaBatch)]
    # 3 unit flushed single (kind change), 1 real single, 8 unit stacked
    # into 2 megabatches, tail [1 unit + ragged] flushed single
    assert len(megas) == 2 and all(m.n_steps == 4 for m in megas)
    assert len(singles) == 6
    assert all(m.val is None for m in megas)     # unit elision survived
    total = sum(m.n_steps for m in megas) + len(singles)
    assert total == len(batches)
    # order: every source batch appears exactly once, in source order
    flat_first_rows = []
    for o in out:
        if isinstance(o, MegaBatch):
            flat_first_rows.extend(np.asarray(o.idx)[i, 0, 0]
                                   for i in range(o.n_steps))
        else:
            flat_first_rows.append(np.asarray(o.idx)[0, 0])
    assert flat_first_rows == [b.idx[0, 0] for b in batches]
    # per-step validity rides nv: ragged batch's 17 is preserved
    assert singles[-1].n_valid == 17


def test_stager_rejects_k1_and_counts_stats():
    from hivemall_tpu.io.pipeline import PipelineStats
    from hivemall_tpu.io.prefetch import MegabatchStager
    with pytest.raises(ValueError):
        MegabatchStager(iter([]), 1)
    rng = np.random.default_rng(13)
    stats = PipelineStats()
    out = list(MegabatchStager(iter([_mk_batch(rng) for _ in range(7)]),
                               3, stats=stats))
    assert stats.steps_per_dispatch == 3
    assert stats.megabatches_staged == 2
    assert stats.singles_flushed == 1
    assert stats.stack_seconds >= 0
    assert len(out) == 3


def test_mega_nv_accounting():
    """n_examples (host-side, no device sync) sums per-step valid rows."""
    from hivemall_tpu.io.prefetch import MegabatchStager
    rng = np.random.default_rng(17)
    batches = [_mk_batch(rng, n_valid=17), _mk_batch(rng, n_valid=17)]
    # same shapes + same kind: n_valid rides nv, windows still stack
    out = list(MegabatchStager(iter(batches), 2))
    assert len(out) == 1 and isinstance(out[0], MegaBatch)
    assert out[0].n_examples == 34
    assert list(out[0].nv) == [17, 17]


# --- donation safety --------------------------------------------------------

def test_donation_safe_across_bundle_and_emission(tmp_path):
    """The megastep donates the state pytree into the scan carry; reading
    the state between fused fits (save_bundle, model_rows) and fitting
    again must equal an uninterrupted pair of fits — no stale donated
    buffer is ever observable."""
    ds = _linear_ds(n=1000, seed=21)
    mk = lambda: GeneralClassifier(
        f"-dims {1 << 12} -mini_batch 256 -opt adagrad "
        f"-steps_per_dispatch 4")
    a, b = mk(), mk()
    a.fit(ds, epochs=1, shuffle=True, prefetch=False)
    a.save_bundle(str(tmp_path / "mid.npz"))
    rows_mid = list(a.model_rows())
    assert rows_mid                      # emission reads post-scan state
    a.fit(ds, epochs=1, shuffle=True, prefetch=False)
    b.fit(ds, epochs=1, shuffle=True, prefetch=False)
    b.fit(ds, epochs=1, shuffle=True, prefetch=False)
    np.testing.assert_allclose(np.asarray(a.w), np.asarray(b.w),
                               rtol=1e-6, atol=1e-8)
    # and the bundle restores into a trainer that can keep fusing
    c = mk()
    c.load_bundle(str(tmp_path / "mid.npz"))
    c.fit(ds, epochs=1, shuffle=True, prefetch=False)
    np.testing.assert_allclose(np.asarray(c.w), np.asarray(a.w),
                               rtol=1e-6, atol=1e-8)


# --- resolution / fallbacks -------------------------------------------------

def test_auto_resolution_and_validation():
    t = GeneralClassifier(f"-dims {1 << 10} -mini_batch 64")
    import jax
    expect = 1 if jax.default_backend() == "cpu" else 8
    assert t._resolved_steps_per_dispatch() == expect
    te = GeneralClassifier(f"-dims {1 << 10} -steps_per_dispatch 5")
    assert te._resolved_steps_per_dispatch() == 5
    with pytest.raises(ValueError):
        GeneralClassifier(
            f"-dims {1 << 10} -steps_per_dispatch -2"
        )._resolved_steps_per_dispatch()


def test_non_scannable_trainer_falls_back_to_k1():
    """Covariance trainers keep bespoke (w, sigma) state — no scannable
    core, so steps_per_dispatch resolves to 1 (their spec doesn't even
    expose the knob) and training is untouched."""
    from hivemall_tpu.models.classifier import AROWTrainer
    t = AROWTrainer(f"-dims {1 << 10} -mini_batch 64")
    assert not t._supports_megastep()
    assert t._resolved_steps_per_dispatch() == 1
    ds = _linear_ds(n=200, L=4, dims=1 << 10, seed=23)
    t.fit(ds, epochs=1, prefetch=False)
    assert t._examples == 200


def test_process_flush_replay_matches_fit_k():
    """The UDTF lifecycle (process/close with -iters replay) also rides
    the K=1 path unchanged with fusion enabled — fused dispatch only
    engages where batches stream through _fit_epochs/fit_stream."""
    rng = np.random.default_rng(29)
    t = GeneralClassifier(f"-dims {1 << 10} -mini_batch 64 -iters 2 "
                          f"-steps_per_dispatch 4")
    for _ in range(150):
        feats = [f"{rng.integers(1, 1000)}:1" for _ in range(4)]
        t.process(feats, float(rng.integers(0, 2) * 2 - 1))
    rows = list(t.close())
    assert rows and t._examples == 300   # 2 epochs x 150 rows
    assert np.isfinite(t.cumulative_loss)


# --- mesh (GSPMD) -----------------------------------------------------------

def test_mesh_k4_matches_mesh_k1():
    """The scan body compiles under GSPMD with the K=1 step's shardings
    (batch rows over dp on axis 1, tables over tp through the donated
    carry) and reproduces the K=1 mesh trajectory."""
    ds = _ffm_ds(n=640, dims=1 << 10)
    make = lambda k: FFMTrainer(
        f"-dims {1 << 10} -factors 4 -fields 8 -mini_batch 128 "
        f"-opt adagrad -classification -mesh dp=2,tp=4 "
        f"-steps_per_dispatch {k}")
    l1, t1 = _trajectory(make, ds, 1)
    l4, t4 = _trajectory(make, ds, 4)
    assert len(l1) == len(l4) == 5
    np.testing.assert_allclose(l4, l1, rtol=1e-5, atol=1e-6)
    T1, T4 = t1.params["T"], t4.params["T"]
    np.testing.assert_allclose(np.asarray(T4, np.float32),
                               np.asarray(T1, np.float32),
                               rtol=1e-5, atol=1e-7)
    # the donated carry preserved the tp sharding
    assert T4.sharding.shard_shape(T4.shape)[0] == t4.Mr // 4


def test_mesh_linear_k4_matches_k1():
    ds = _linear_ds(n=640, dims=1 << 10, seed=31, unit=False)
    make = lambda k: GeneralClassifier(
        f"-dims {1 << 10} -mini_batch 128 -opt adagrad -mesh dp=4,tp=2 "
        f"-steps_per_dispatch {k}")
    l1, t1 = _trajectory(make, ds, 1)
    l4, t4 = _trajectory(make, ds, 4)
    np.testing.assert_allclose(l4, l1, rtol=1e-5, atol=1e-6)
    w4 = t4.w
    assert w4.sharding.shard_shape(w4.shape)[0] == (1 << 10) // 2


def test_shard_cached_k8_matches_k1_streamed(tmp_path):
    """The packed shard cache (round 6, -shard_cache_dir) feeds the SAME
    megabatch stacking the streamed path uses: warm (mmap-served) epochs
    at K=1 and K=8 reproduce the streamed K=1 trajectory bit-exactly, and
    K=8 actually forms fused windows from the cached PackedBatches."""
    ds = _ffm_ds(n=4096, dims=1 << 12, seed=40)
    cdir = str(tmp_path / "cache")

    def make(k, cache):
        extra = f" -shard_cache_dir {cdir}" if cache else ""
        return FFMTrainer(
            f"-dims {1 << 12} -factors 2 -fields 8 -mini_batch 256 "
            f"-classification -pack_input on -steps_per_dispatch {k}"
            + extra)

    def traj(k, cache):
        t = make(k, cache)
        t._trace_losses = []
        t.fit(ds, epochs=1, shuffle=True)
        return np.asarray(t._trace_losses), t

    l1, _ = traj(1, False)                   # streamed reference
    l1_cold, _ = traj(1, True)               # cold: builds the cache
    l1_warm, t1w = traj(1, True)             # warm K=1
    l8_warm, t8w = traj(8, True)             # warm K=8 through the stager
    np.testing.assert_array_equal(l1, l1_cold)
    np.testing.assert_array_equal(l1, l1_warm)
    np.testing.assert_array_equal(l1, l8_warm)
    # warm runs never prep; K=8 stacked the cached batches into megasteps
    assert t1w.pipeline_stats.batches_prepared == 0
    assert t8w.pipeline_stats.batches_prepared == 0
    assert t8w.pipeline_stats.megabatches_staged == 2   # 16 batches @ K=8
    assert t8w.pipeline_stats.cache_batches == 16
    # a COLD build under K=8 (tee sits before the stager, so it records
    # singles) produces a cache a K=1 warm run replays bit-exactly too
    import shutil
    shutil.rmtree(cdir)
    l8_cold, t8c = traj(8, True)
    np.testing.assert_array_equal(l1, l8_cold)
    assert t8c.pipeline_stats.megabatches_staged == 2
    l1_warm2, _ = traj(1, True)
    np.testing.assert_array_equal(l1, l1_warm2)
