"""Observability subsystem (SURVEY.md §6): jsonl stream, meter, trainer hook."""

import json
import time

import numpy as np

from hivemall_tpu.models.linear import GeneralClassifier
from hivemall_tpu.utils import metrics as M


def test_meter_rate():
    m = M.Meter(window=60.0)
    m.add(100)
    time.sleep(0.05)
    m.add(100)
    assert m.total == 200
    assert m.rate > 0


def test_stream_disabled_is_noop():
    s = M.MetricsStream(None)
    assert not s.enabled
    s.emit("anything", x=1)      # must not raise


def test_stream_writes_jsonl(tmp_path):
    p = tmp_path / "m.jsonl"
    s = M.MetricsStream(str(p))
    s.emit("ev", a=1)
    s.emit("ev", a=2)
    s.close()
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    assert [r["a"] for r in recs] == [1, 2]
    assert all(r["event"] == "ev" and "ts" in r and "host" in r
               for r in recs)


def test_trainer_emits_stream(tmp_path, monkeypatch):
    p = tmp_path / "train.jsonl"
    monkeypatch.setattr(M, "_stream", M.MetricsStream(str(p)))
    rng = np.random.default_rng(0)
    tr = GeneralClassifier("-mini_batch 16 -dims 1024")
    for i in range(40):
        x = rng.normal(size=3)
        y = 1 if x.sum() > 0 else -1
        tr.process([f"f{j}:{x[j]:.4f}" for j in range(3)], y)
    rows = list(tr.close())
    assert rows
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    done = [r for r in recs if r["event"] == "train_done"]
    assert len(done) == 1
    assert done[0]["examples"] == 40
    assert done[0]["trainer"] == tr.NAME
    M._stream.close()
    monkeypatch.setattr(M, "_stream", None)


def test_stream_bad_path_fails_soft(capsys):
    s = M.MetricsStream("/nonexistent-dir-xyz/m.jsonl")
    assert not s.enabled
    s.emit("ev", a=1)            # still a no-op, no raise
    assert "metrics disabled" in capsys.readouterr().err


def test_profile_trace_noop():
    with M.profile_trace(None):
        pass
