"""leaktrack FD/socket/thread census sanitizer tests
(docs/STATIC_ANALYSIS.md — GC12's dynamic twin).

Same contract as the tsan tests: the sanitizer must CATCH a seeded
leak (with the creation stack attributed), stay SILENT on the closed
twin, restore the creation surface on disable, and emit the JSONL
artifact records the smokes collect.
"""

import json
import os
import socket
import threading
import time

import pytest

from hivemall_tpu.testing import leaktrack


@pytest.fixture()
def sanitizer():
    """Enable around the test, restore and reset afterwards."""
    was = leaktrack.enabled()
    leaktrack.enable()
    leaktrack.snapshot()
    try:
        yield leaktrack
    finally:
        leaktrack.reset()
        if not was:
            leaktrack.disable()


def test_seeded_socket_leak_caught_with_stack(sanitizer):
    a, b = socket.socketpair()
    try:
        got = leaktrack.leaks(grace_s=0.0)
        socks = [r for r in got["tracked"] if r["kind"] == "socket"]
        assert len(socks) == 2
        # attribution: the creation stack names THIS test
        assert "test_seeded_socket_leak_caught_with_stack" \
            in socks[0]["stack"]
    finally:
        a.close()
        b.close()


def test_closed_twin_clean(sanitizer):
    a, b = socket.socketpair()
    a.close()
    b.close()
    got = leaktrack.leaks(grace_s=0.0)
    assert got["tracked"] == []


def test_seeded_file_leak_caught(sanitizer, tmp_path):
    p = tmp_path / "leak.txt"
    p.write_text("x")                    # closed by write_text: clean
    f = open(p)                          # noqa: SIM115 — the seeded leak
    try:
        got = leaktrack.leaks(grace_s=0.0)
        files = [r for r in got["tracked"] if r["kind"] == "file"
                 and "leak.txt" in r["repr"]]
        assert len(files) == 1
    finally:
        f.close()
    assert [r for r in leaktrack.leaks(grace_s=0.0)["tracked"]
            if "leak.txt" in r["repr"]] == []


def test_dropped_handle_is_gc_lag_not_leak(sanitizer, tmp_path):
    """A handle DROPPED without close is collected by the census's own
    gc sweep — GC lag must not read as a leak."""
    p = tmp_path / "dropped.txt"
    p.write_text("x")
    open(p)                              # noqa: SIM115 — ref dropped
    got = leaktrack.leaks(grace_s=0.0)
    assert [r for r in got["tracked"] if "dropped.txt" in r["repr"]] == []


def test_thread_leak_caught_and_joined_clean(sanitizer):
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="leaky-worker",
                         daemon=True)
    t.start()
    try:
        got = leaktrack.leaks(grace_s=0.0)
        names = [r["name"] for r in got["threads"]]
        assert "leaky-worker" in names
        rec = next(r for r in got["threads"]
                   if r["name"] == "leaky-worker")
        assert "test_thread_leak" in rec["stack"]   # attribution
    finally:
        stop.set()
        t.join(timeout=5)
    got = leaktrack.leaks(grace_s=0.0)
    assert [r for r in got["threads"]
            if r["name"] == "leaky-worker"] == []


def test_thread_grace_absorbs_late_join(sanitizer):
    """A worker still draining when the census starts must pass once it
    exits within the grace window."""
    t = threading.Thread(target=lambda: time.sleep(0.3),
                         name="late-join", daemon=True)
    t.start()
    got = leaktrack.leaks(grace_s=3.0)
    assert [r for r in got["threads"] if r["name"] == "late-join"] == []
    t.join(timeout=5)


def test_pre_snapshot_resources_exempt():
    was = leaktrack.enabled()
    leaktrack.enable()
    try:
        a, b = socket.socketpair()       # born BEFORE the snapshot
        try:
            leaktrack.snapshot()
            got = leaktrack.leaks(grace_s=0.0)
            assert got["tracked"] == []
        finally:
            a.close()
            b.close()
    finally:
        leaktrack.reset()
        if not was:
            leaktrack.disable()


def test_check_and_report_emits_jsonl(sanitizer, tmp_path, monkeypatch):
    log = tmp_path / "census.jsonl"
    monkeypatch.setenv(leaktrack.ENV_LOG, str(log))
    a, b = socket.socketpair()
    try:
        n = leaktrack.check_and_report("unit-test")
        assert n == 2
        records = [json.loads(line)
                   for line in log.read_text().splitlines()]
        kinds = [r["kind"] for r in records]
        assert kinds.count("socket") == 2
        summary = next(r for r in records if r["kind"] == "summary")
        assert summary["leaks"] == 2 and summary["label"] == "unit-test"
    finally:
        a.close()
        b.close()
    assert leaktrack.check_and_report("unit-test-clean") == 0


def test_report_child_leaks_counts_replica_summaries(tmp_path,
                                                     monkeypatch):
    """The parent smoke's gate folds in replica-worker censuses: only
    ``replica:`` summary records appended AFTER the recorded offset
    count; the parent's own summary and pre-offset records do not."""
    log = tmp_path / "census.jsonl"
    monkeypatch.setenv(leaktrack.ENV_LOG, str(log))
    stale = {"label": "replica:1 leaktrack", "kind": "summary",
             "leaks": 9, "fd_delta": 9, "new_fds": []}
    log.write_text(json.dumps(stale) + "\n")     # an earlier CI leg
    off = leaktrack.log_offset()
    assert off == len(log.read_bytes())
    with log.open("a") as fh:
        fh.write(json.dumps({"label": "replica:2 leaktrack",
                             "kind": "summary", "leaks": 2,
                             "fd_delta": 2, "new_fds": []}) + "\n")
        fh.write(json.dumps({"label": "replica:3 leaktrack",
                             "kind": "summary", "leaks": 0,
                             "fd_delta": 0, "new_fds": []}) + "\n")
        fh.write(json.dumps({"label": "fleet smoke leaktrack",
                             "kind": "summary", "leaks": 5,
                             "fd_delta": 5, "new_fds": []}) + "\n")
        fh.write(json.dumps({"label": "replica:4 leaktrack",
                             "kind": "socket", "fd": 7,
                             "stack": "..."}) + "\n")
    assert leaktrack.report_child_leaks(off) == 2
    assert leaktrack.report_child_leaks(0) == 11  # stale leg included
    monkeypatch.delenv(leaktrack.ENV_LOG)
    assert leaktrack.log_offset() == 0
    assert leaktrack.report_child_leaks(0) == 0


def test_selfcheck_preserves_live_census(sanitizer):
    """An in-process selfcheck run hands back the caller's census: the
    snapshot object and already-tracked leaks survive it (a reset would
    both drop real leaks and false-positive on pre-existing threads at
    the caller's own check_and_report)."""
    snap_before = leaktrack._snap
    a, b = socket.socketpair()
    try:
        ok, detail = leaktrack.selfcheck_leak()
        assert ok, detail
        assert leaktrack._snap is snap_before
        got = leaktrack.leaks(grace_s=0.0)
        socks = [r for r in got["tracked"] if r["kind"] == "socket"]
        assert len(socks) == 2           # the caller's leak still seen
    finally:
        a.close()
        b.close()


def test_env_negatives_stay_disabled(monkeypatch):
    for v in ("0", "false", "False", "NO", "off", ""):
        monkeypatch.setenv(leaktrack.ENV_FLAG, v)
        if not leaktrack.enabled():
            assert leaktrack.maybe_enable() is False, v


def test_disable_restores_creation_surface():
    was = leaktrack.enabled()
    if was:
        pytest.skip("sanitizer enabled by the environment")
    orig_socket = socket.socket
    orig_open = open
    leaktrack.enable()
    try:
        assert socket.socket is not orig_socket
    finally:
        leaktrack.disable()
        leaktrack.reset()
    assert socket.socket is orig_socket
    assert open is orig_open             # builtins restored


def test_accept_and_create_connection_are_attributed(sanitizer):
    """create_server/create_connection/accept all construct through the
    module-level class — every wire socket is born tracked."""
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    cli = socket.create_connection(("127.0.0.1", port))
    conn, _ = srv.accept()
    try:
        got = leaktrack.leaks(grace_s=0.0)
        socks = [r for r in got["tracked"] if r["kind"] == "socket"]
        assert len(socks) >= 3           # server + client + accepted
    finally:
        conn.close()
        cli.close()
        srv.close()
    assert [r for r in leaktrack.leaks(grace_s=0.0)["tracked"]
            if r["kind"] == "socket"] == []


def test_selfcheck_leak_bidirectional():
    ok, detail = leaktrack.selfcheck_leak()
    assert ok, detail
    assert "detected" in detail and "clean" in detail
