"""Golden convergence tests on committed dataset-shaped fragments.

Reference practice (SURVEY.md §5.2): LIBSVM snippets as test resources with
convergence-smoke assertions ("loss decreases; AUC above threshold"), and
BASELINE's quality metric is logloss@1 epoch. The fragments are synthetic
but dataset-shaped (no network access in this environment — see
tests/resources/make_fragments.py for the matched statistics and the
seed-pinned generator); thresholds carry margin over the calibrated runs:
a9a-frag 1-epoch AdaGrad logloss 0.43 / AUC 0.93, FM 0.33 / 0.93,
news20b-frag 0.05, MovieLens-frag MF RMSE 0.72 vs 0.81 global-mean floor.
"""

import os

import numpy as np
import pytest

from hivemall_tpu.frame.evaluation import auc, logloss, rmse
from hivemall_tpu.io.libsvm import read_libsvm

RES = os.path.join(os.path.dirname(__file__), "resources")


@pytest.fixture(scope="module")
def a9a():
    return (read_libsvm(os.path.join(RES, "a9a.frag.train.libsvm")),
            read_libsvm(os.path.join(RES, "a9a.frag.test.libsvm")))


@pytest.fixture(scope="module")
def news20b():
    return (read_libsvm(os.path.join(RES, "news20b.frag.train.libsvm")),
            read_libsvm(os.path.join(RES, "news20b.frag.test.libsvm")))


@pytest.fixture(scope="module")
def movielens():
    m = np.loadtxt(os.path.join(RES, "movielens.frag.tsv"))
    u = m[:, 0].astype(np.int32)
    i = m[:, 1].astype(np.int32)
    r = m[:, 2].astype(np.float32)
    split = int(len(u) * 0.8)
    return (u[:split], i[:split], r[:split]), (u[split:], i[split:],
                                               r[split:])


def test_a9a_logloss_at_one_epoch(a9a):
    """BASELINE's metric shape: logloss@1 epoch, train_classifier AdaGrad."""
    from hivemall_tpu.models.linear import GeneralClassifier
    tr, te = a9a
    c = GeneralClassifier("-dims 256 -loss logloss -opt adagrad -reg no "
                          "-eta0 0.1 -mini_batch 64")
    c.fit(tr, epochs=1)
    p = c.predict_proba(te)
    assert logloss(te.labels, p) < 0.48
    assert auc(te.labels, p) > 0.90


def test_a9a_fm_one_epoch(a9a):
    from hivemall_tpu.models.fm import FMTrainer
    tr, te = a9a
    f = FMTrainer("-dims 256 -factors 4 -classification -opt adagrad "
                  "-eta0 0.1 -mini_batch 64 -lambda_w 0 -lambda_v 0.001")
    f.fit(tr, epochs=1)
    p = f.predict(te)
    assert logloss(te.labels, p) < 0.40
    assert auc(te.labels, p) > 0.90


def test_news20b_high_dim_sparse(news20b):
    """news20.binary shape: 2^20 hashed dims, ~150 nnz tf-idf rows."""
    from hivemall_tpu.models.linear import GeneralClassifier
    tr, te = news20b
    c = GeneralClassifier("-dims 1048576 -loss logloss -opt adagrad "
                          "-reg no -eta0 0.5 -mini_batch 64")
    c.fit(tr, epochs=1)
    p = c.predict_proba(te)
    assert logloss(te.labels, p) < 0.15
    assert auc(te.labels, p) > 0.99


def test_movielens_mf_beats_global_mean(movielens):
    from hivemall_tpu.models.mf import MFAdaGradTrainer
    (u, i, r), (ut, it, rt) = movielens
    floor = float(np.sqrt(((rt - 3.6) ** 2).mean()))
    m = MFAdaGradTrainer("-factors 8 -users 400 -items 300 -mini_batch 256 "
                         "-eta0 0.1 -mu 3.6")
    m.fit(u, i, r, epochs=1)
    e1 = rmse(rt, m.predict(ut, it))
    assert e1 < 0.78
    assert e1 < floor - 0.05
    m.fit(u, i, r, epochs=4)
    assert rmse(rt, m.predict(ut, it)) < 0.76


def test_a9a_loss_decreases_across_epochs(a9a):
    from hivemall_tpu.models.linear import GeneralClassifier
    tr, te = a9a
    losses = []
    for ep in (1, 3):
        c = GeneralClassifier("-dims 256 -loss logloss -opt adagrad "
                              "-reg no -eta0 0.1 -mini_batch 64")
        c.fit(tr, epochs=ep)
        losses.append(logloss(te.labels, c.predict_proba(te)))
    assert losses[1] < losses[0]


def test_criteo_ffm_fragment_beats_linear():
    """The FFM fragment's labels are dominated by field-pair interactions:
    train_ffm (both layouts) must clearly beat train_classifier on AUC —
    the capability the model family exists for."""
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer
    from hivemall_tpu.models.linear import GeneralClassifier

    rows, labels = [], []
    for line in open(os.path.join(RES, "criteo_ffm.frag.tsv")):
        y, _, feats = line.rstrip().partition("\t")
        labels.append(float(y))
        rows.append(feats.split())
    split = int(len(rows) * 0.8)

    probe = FFMTrainer("-dims 4096 -fields 6")
    parsed = [probe._parse_row(r) for r in rows]
    tr = SparseDataset.from_rows([(i, v) for i, v, f in parsed[:split]],
                                 labels[:split],
                                 [f for i, v, f in parsed[:split]])
    te = SparseDataset.from_rows([(i, v) for i, v, f in parsed[split:]],
                                 labels[split:],
                                 [f for i, v, f in parsed[split:]])
    y_te = np.asarray(labels[split:])

    aucs = {}
    for name, extra in (("joint", ""), ("dense", ""),
                        ("joint-pairs", "-ffm_interaction pairs")):
        layout = name.split("-")[0]
        f = FFMTrainer("-dims 4096 -factors 4 -fields 6 -mini_batch 64 "
                       "-classification -opt adagrad -eta0 0.2 -iters 20 "
                       f"-lambda_v 0 -lambda_w 0 -sigma 0.05 "
                       f"-ffm_table {layout} {extra}")
        f.fit(tr)
        aucs[name] = auc(y_te, f.predict(te))
    # the canonical field-major kernel and the general pair kernel are the
    # same optimization — real-data AUC must agree closely
    assert abs(aucs["joint"] - aucs["joint-pairs"]) < 0.02, aucs

    lin = GeneralClassifier("-dims 4096 -loss logloss -opt adagrad -reg no "
                            "-mini_batch 64 -iters 20")
    lin.fit(tr)
    lin_auc = auc(y_te, lin.predict_proba(te))

    assert aucs["joint"] > 0.70, aucs
    assert aucs["dense"] > 0.70, aucs
    assert min(aucs.values()) > lin_auc + 0.08, (aucs, lin_auc)
