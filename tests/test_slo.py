"""SLO engine (hivemall_tpu/obs/slo.py, docs/OBSERVABILITY.md "Serving
traces and SLOs"): windowed error-budget burn rates off cumulative
serving totals, changefinder drift detection over the latency and
prediction-score streams, and the serve/fleet wiring (/slo endpoints,
healthz totals aggregation). Samples carry explicit timestamps so every
window computation is deterministic — no sleeps."""

import json

import pytest

import hivemall_tpu.utils.metrics as M
from hivemall_tpu.obs.histo import LATENCY_BUCKETS_S, Histogram
from hivemall_tpu.obs.slo import SloEngine


def _totals(requests, bad, lat_ms_per_req, *, hist=None, score=None):
    """Build cumulative totals: ``lat_ms_per_req`` is a list of ALL
    request latencies so far (cumulative, like the live histogram)."""
    h = Histogram(LATENCY_BUCKETS_S)
    for ms in lat_ms_per_req:
        h.observe(ms / 1000.0)
    t = {"requests": requests, "errors": bad, "shed": 0,
         "latency": h.snapshot()}
    if score is not None:
        n = len(score)
        t.update(score_sum=float(sum(score)),
                 score_sumsq=float(sum(x * x for x in score)),
                 score_n=n)
    return t


def test_slo_engine_window_diffs_and_availability_burn():
    """Samples every 60s for 10 minutes: the 5m window diffs against the
    sample AT its far edge and sees only the second half's failures; the
    1h window (longer than history) covers everything."""
    e = SloEngine(p99_ms=100.0, availability=0.99)
    t0 = 1_000_000.0
    lats = []
    # first 5 minutes: 20 good requests per tick
    for i in range(6):                  # t0 .. t0+300
        lats = [5.0] * (20 * i)
        e.sample(_totals(20 * i, 0, lats), ts=t0 + 60 * i)
    # second 5 minutes: 10 requests per tick, 2 of them bad
    for j in range(1, 6):               # t0+360 .. t0+600
        lats = [5.0] * (100 + 10 * j)
        e.sample(_totals(100 + 10 * j, 2 * j, lats), ts=t0 + 300 + 60 * j)
    out = e.evaluate(now=t0 + 600)
    w5, w1h = out["windows"]["5m"], out["windows"]["1h"]
    assert w5["requests"] == 50 and w5["bad"] == 10
    assert w5["availability"] == pytest.approx(0.8)
    # bad fraction 0.2 vs budget 0.01 -> 20x burn
    assert w5["availability_burn_rate"] == pytest.approx(20.0)
    # the 1h window spans the whole history
    assert w1h["requests"] == 150 and w1h["bad"] == 10
    assert w1h["availability_burn_rate"] == pytest.approx(
        (10 / 150) / 0.01, rel=1e-3)
    assert w5["qps"] == pytest.approx(50 / 300, abs=0.01)   # rounded 2dp


def test_slo_latency_burn_moves_on_injected_regression():
    """Acceptance: burn rates MOVE when a latency regression is
    injected. Steady 5ms traffic is inside a 100ms p99 budget; flipping
    new requests to 400ms pushes the 5m frac-over and burn rate up while
    the pre-regression window stays clean."""
    e = SloEngine(p99_ms=100.0, availability=0.999)
    t0 = 2_000_000.0
    lats = []
    n = 0
    for i in range(5):                  # 5 ticks of healthy traffic
        lats += [5.0] * 20
        n += 20
        e.sample(_totals(n, 0, lats), ts=t0 + i)
    healthy = e.evaluate(now=t0 + 4)["windows"]["5m"]
    assert healthy["latency_burn_rate"] == 0.0
    assert healthy["p99_ms"] is not None and healthy["p99_ms"] < 100.0
    # inject the regression: every new request takes 400ms
    for i in range(5, 10):
        lats += [400.0] * 20
        n += 20
        e.sample(_totals(n, 0, lats), ts=t0 + i)
    bad = e.evaluate(now=t0 + 9)["windows"]["5m"]
    assert bad["frac_over_slo"] > 0.4
    assert bad["latency_burn_rate"] > 40.0       # >> 1x: budget burning
    assert bad["p99_ms"] > 100.0


def test_slo_changefinder_flags_drift_into_metrics_stream(tmp_path,
                                                          monkeypatch):
    """Acceptance: the changefinder flags the injected regression in the
    metrics stream — an `slo_drift` record lands in the jsonl next to
    train/serve telemetry, and the drift counters move."""
    p = tmp_path / "m.jsonl"
    monkeypatch.setattr(M, "_stream", M.MetricsStream(str(p)))
    try:
        e = SloEngine(p99_ms=100.0, drift_warmup=20, drift_sigma=6.0)
        t0 = 3_000_000.0
        lats = []
        n = 0
        # long steady phase calibrates the detector's change-score scale
        for i in range(60):
            lats += [5.0, 5.2, 4.8, 5.1]
            n += 4
            e.sample(_totals(n, 0, lats), ts=t0 + i)
        assert e.drift_counts["latency_ms"] == 0
        # step change: sustained 30x latency
        for i in range(60, 90):
            lats += [150.0, 151.0, 149.0, 150.5]
            n += 4
            e.sample(_totals(n, 0, lats), ts=t0 + i)
        assert e.drift_counts["latency_ms"] >= 1
        assert e.drift_events and \
            e.drift_events[-1]["series"] == "latency_ms"
        M._stream.close()
        drift = [json.loads(line) for line in open(p)
                 if json.loads(line).get("event") == "slo_drift"]
        assert drift and drift[0]["series"] == "latency_ms"
        assert drift[0]["change_score"] > 0
    finally:
        M._stream = None


def test_slo_score_drift_detected():
    """A prediction-score distribution shift (0.5 -> 0.9 mean) flags the
    score-series changefinder. The long steady phase lets the SDAR
    variance converge to the series' real (small) noise floor, so the
    step registers at full significance — the live sampler ticks every
    second, so 300 ticks is five minutes of calibration."""
    import random
    rng = random.Random(7)
    e = SloEngine(drift_warmup=20, drift_sigma=6.0)
    t0 = 4_000_000.0
    n = 0
    scores = []
    for i in range(300):                # stable score distribution
        scores += [0.5 + rng.uniform(-0.02, 0.02) for _ in range(3)]
        n += 3
        e.sample(_totals(n, 0, [5.0] * n, score=scores), ts=t0 + i)
    assert e.drift_counts["score"] == 0
    for i in range(300, 330):           # the model starts scoring high
        scores += [0.9 + rng.uniform(-0.02, 0.02) for _ in range(3)]
        n += 3
        e.sample(_totals(n, 0, [5.0] * n, score=scores), ts=t0 + i)
    assert e.drift_counts["score"] >= 1
    assert any(ev["series"] == "score" for ev in e.drift_events)
    # drift-driven retrain hook (ROADMAP item 2): every score drift is a
    # retrain_wanted vote, surfaced on /slo and the slo registry section
    assert e.retrain_wanted == e.drift_counts["score"]
    assert e.evaluate()["drift"]["retrain_wanted"] == e.retrain_wanted
    assert e.obs_section()["retrain_wanted"] == e.retrain_wanted


def test_slo_counter_reset_clamps_never_negative():
    """A replica respawn resets its cumulative share — window diffs must
    clamp at zero, not report negative rates."""
    e = SloEngine()
    t0 = 5_000_000.0
    e.sample(_totals(1000, 5, [5.0] * 100), ts=t0)
    e.sample(_totals(50, 0, [5.0] * 10), ts=t0 + 10)   # reset mid-window
    out = e.evaluate(now=t0 + 10)
    w = out["windows"]["5m"]
    assert w["requests"] == 0 and w["bad"] == 0
    assert w["availability_burn_rate"] == 0.0


def test_slo_registry_section_and_validation():
    from hivemall_tpu.obs.registry import registry
    e = SloEngine(p99_ms=50.0)
    snap = registry.snapshot()
    assert snap["slo"]["configured"] is True
    assert snap["slo"]["target_p99_ms"] == 50.0
    del e                                # weakly held: falls back to stub
    import gc
    gc.collect()
    from hivemall_tpu.obs.registry import SLO_STUB
    assert registry.snapshot()["slo"] == SLO_STUB
    with pytest.raises(ValueError, match="availability"):
        SloEngine(availability=1.5)


def test_predict_server_slo_endpoint(tmp_path):
    """/slo on a live PredictServer: sampled from its own batcher."""
    import os
    import time
    import urllib.request
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.serve.engine import PredictEngine
    from hivemall_tpu.serve.http import KeepAliveClient, PredictServer
    opts = "-dims 512 -loss logloss -opt adagrad -mini_batch 32"
    ds, _ = synthetic_classification(60, 32, seed=3)
    t = GeneralClassifier(opts)
    t.fit(ds)
    t.save_bundle(os.path.join(tmp_path, f"{t.NAME}-step{t._t:010d}.npz"))
    eng = PredictEngine("train_classifier", opts,
                        checkpoint_dir=str(tmp_path), warmup=False)
    srv = PredictServer(eng, port=0, max_delay_ms=1.0, watch=False,
                        slo_p99_ms=250.0).start()
    # the sampler thread ticks at 1s; sample synchronously instead so
    # the test stays fast and deterministic
    srv.slo.stop()
    try:
        cli = KeepAliveClient("127.0.0.1", srv.port)
        rows = [[f"{int(a)}:{float(v)!r}" for a, v in zip(*ds.row(0))]]
        for _ in range(3):
            code, _ = cli.post_json("/predict", {"rows": rows})
            assert code == 200
        srv.slo.sample(srv.batcher.slo_totals(), ts=time.time())
        out = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/slo", timeout=10).read())
        assert out["configured"] and out["targets"]["p99_ms"] == 250.0
        assert out["windows"]["5m"]["requests"] == 3
        assert out["score"] is not None
        cli.close()
    finally:
        srv.stop()


def test_fleet_slo_totals_aggregate_across_replicas():
    """The manager's fleet-wide sum: per-replica /healthz slo sections
    (histogram buckets, counters, score moments) add exactly."""
    from hivemall_tpu.serve.fleet import ReplicaManager

    class _R:
        def __init__(self, rid, slo):
            self.rid = rid
            self.last_health = {"slo": slo}

    mgr = ReplicaManager.__new__(ReplicaManager)   # no processes needed
    mgr._slo_seen = {}
    a = Histogram(LATENCY_BUCKETS_S)
    b = Histogram(LATENCY_BUCKETS_S)
    for ms in (1.0, 2.0):
        a.observe(ms / 1000.0)
    b.observe(0.5)
    reps = [
        _R("r0", {"requests": 10, "errors": 1, "shed": 2, "expired": 1,
                  "latency": a.snapshot(),
                  "score_sum": 5.0, "score_sumsq": 2.6, "score_n": 10}),
        _R("r1", {"requests": 4, "errors": 0, "shed": 0,
                  "latency": b.snapshot(),
                  "score_sum": 2.0, "score_sumsq": 1.1, "score_n": 4}),
        _R("r2", None),                 # replica not yet probed: skipped
    ]
    mgr.replicas = lambda: reps
    tot = mgr._slo_totals()
    assert tot["requests"] == 14 and tot["errors"] == 1
    assert tot["shed"] == 2 and tot["expired"] == 1
    assert tot["score_n"] == 14
    assert tot["latency"]["count"] == 3
    assert tot["latency"]["buckets"][-1][1] == 3   # +Inf sums bucket-wise
    assert tot["score_sum"] == pytest.approx(7.0)
    assert tot["reset"] is False
    # a replica respawning (rid vanishes, replacement starts at 0) flags
    # the NEXT tick as reset so the drift feed skips the garbage interval
    reps[0] = _R("r3", {"requests": 0, "errors": 0, "shed": 0,
                        "latency": b.snapshot(),
                        "score_sum": 0.0, "score_sumsq": 0.0,
                        "score_n": 0})
    tot2 = mgr._slo_totals()
    assert tot2["reset"] is True
    assert mgr._slo_totals()["reset"] is False     # steady again


def test_slo_expired_requests_burn_availability():
    """504s are client-visible failures: the expired counter burns the
    availability budget alongside errors and shed."""
    e = SloEngine(availability=0.99)
    t0 = 6_000_000.0
    t = _totals(100, 0, [5.0] * 100)
    e.sample(dict(t), ts=t0)
    t2 = _totals(200, 0, [5.0] * 200)
    t2["expired"] = 50                   # half the new traffic timed out
    e.sample(t2, ts=t0 + 10)
    w = e.evaluate(now=t0 + 10)["windows"]["5m"]
    assert w["bad"] == 50
    assert w["availability"] == pytest.approx(0.5)


def test_slo_reset_flag_skips_drift_feed():
    """A totals dict flagged reset=True still folds into the windows but
    never reaches the changefinder (no garbage interval means)."""
    e = SloEngine(drift_warmup=0, drift_sigma=0.1)
    t0 = 7_000_000.0
    for i in range(10):
        e.sample(_totals(10 * (i + 1), 0, [5.0] * 10 * (i + 1)),
                 ts=t0 + i)
    fed = e._watch["latency_ms"].n
    t = _totals(200, 0, [5.0] * 100 + [500.0] * 100)
    t["reset"] = True
    e.sample(t, ts=t0 + 10)
    assert e._watch["latency_ms"].n == fed                    # skipped
    assert e.evaluate(now=t0 + 10)["windows"]["5m"]["requests"] == 190


def test_slo_shed_burns_but_never_negative_availability():
    """Shed submits never enter the batcher's accepted-requests counter,
    so availability must divide by OFFERED (accepted + shed) — overload
    reads as low availability, never as a negative one."""
    e = SloEngine(availability=0.99)
    t0 = 8_000_000.0
    e.sample(_totals(0, 0, []), ts=t0)
    t = _totals(10, 0, [5.0] * 10)      # 10 accepted...
    t["shed"] = 90                      # ...90 shed at the door
    e.sample(t, ts=t0 + 10)
    w = e.evaluate(now=t0 + 10)["windows"]["5m"]
    assert w["requests"] == 100         # offered
    assert w["bad"] == 90
    assert w["availability"] == pytest.approx(0.1)
    assert w["availability_burn_rate"] == pytest.approx(90.0)


def test_slo_partial_reset_keeps_latency_metrics_in_range():
    """A partial fleet reset (one replica's histogram history vanishes
    while survivors keep counting) must not produce a negative over-SLO
    fraction or an out-of-range p99 — the bucket diff is re-monotonized."""
    from hivemall_tpu.obs.slo import _diff_buckets
    # old edge: 500 slow requests (0.25s bucket); new: those vanished,
    # survivors added 600 fast ones
    old = [[0.005, 0], [0.25, 500], ["+Inf", 500]]
    new = [[0.005, 600], [0.25, 600], ["+Inf", 600]]
    diff = _diff_buckets(new, old)
    counts = [c for _, c in diff]
    assert counts == sorted(counts)      # monotone cumulative again
    assert all(c >= 0 for c in counts)
    e = SloEngine(p99_ms=100.0)
    t0 = 9_000_000.0
    e.sample({"requests": 500, "latency":
              {"buckets": old, "sum": 100.0, "count": 500}}, ts=t0)
    e.sample({"requests": 1100, "latency":
              {"buckets": new, "sum": 103.0, "count": 600}}, ts=t0 + 10)
    w = e.evaluate(now=t0 + 10)["windows"]["5m"]
    assert w["frac_over_slo"] >= 0.0
    assert w["latency_burn_rate"] >= 0.0
    assert w["p99_ms"] is None or w["p99_ms"] >= 0.0


def test_batcher_fallback_rescore_feeds_score_moments():
    """Requests scored through the error-isolation fallback stay visible
    to the score-drift detector."""
    import threading
    import numpy as np
    from hivemall_tpu.serve.batcher import MicroBatcher
    calls = []

    def flaky(rows):
        calls.append(len(rows))
        if len(calls) == 2 and len(rows) > 1:
            raise RuntimeError("batch poisoned")    # coalesced batch dies
        return np.full(len(rows), 0.5, np.float32)

    gate = threading.Event()

    def gated(rows):
        if len(calls) == 0:
            calls.append(len(rows))
            gate.wait(5)
            return np.full(len(rows), 0.5, np.float32)
        return flaky(rows)

    b = MicroBatcher(gated, max_batch=8, max_delay_ms=1.0)
    try:
        f0 = b.submit([("w",)])          # occupies the dispatch thread
        f1 = b.submit([("a",)])          # these two coalesce and the
        f2 = b.submit([("b",)])          # batch raises -> per-request
        gate.set()                       # fallback
        for f in (f0, f1, f2):
            f.result(5)
        assert b.score_n == 3            # fallback requests counted
        assert b.stats()["score_mean"] == pytest.approx(0.5)
    finally:
        b.close()


def test_slo_partial_reset_availability_never_negative():
    """Partial reset where the bad delta survives the clamp harder than
    the offered delta: availability is bounded at >= 0 (bad <= offered)."""
    e = SloEngine(availability=0.999)
    t0 = 10_000_000.0
    # edge: replica A 1000 good + replica B 100 req / 10 bad
    e.sample({"requests": 1100, "errors": 10}, ts=t0)
    # A respawned near zero while B shed hard: fleet sums go 1150/60
    e.sample({"requests": 1150, "errors": 60}, ts=t0 + 10)
    w = e.evaluate(now=t0 + 10)["windows"]["5m"]
    assert w["bad"] <= w["requests"]
    assert 0.0 <= w["availability"] <= 1.0
    assert w["availability_burn_rate"] >= 0.0


def test_slo_window_score_mean_suppressed_on_inconsistent_moments():
    """Window score moments that fail the consistency check (a partial
    reset subtracted a dead replica's sumsq) are suppressed, not served
    as garbage."""
    e = SloEngine()
    t0 = 11_000_000.0
    e.sample({"requests": 100, "score_sum": 80.0, "score_sumsq": 70.0,
              "score_n": 100}, ts=t0)
    # partial reset: n grew but the dead replica's sumsq vanished
    e.sample({"requests": 150, "score_sum": 85.0, "score_sumsq": 20.0,
              "score_n": 150}, ts=t0 + 10)
    w = e.evaluate(now=t0 + 10)["windows"]["5m"]
    assert "score_mean" not in w         # dss < 0: suppressed
    # healthy moments still report
    e2 = SloEngine()
    e2.sample({"requests": 10, "score_sum": 5.0, "score_sumsq": 2.6,
               "score_n": 10}, ts=t0)
    e2.sample({"requests": 20, "score_sum": 10.0, "score_sumsq": 5.2,
               "score_n": 20}, ts=t0 + 10)
    w2 = e2.evaluate(now=t0 + 10)["windows"]["5m"]
    assert w2["score_mean"] == pytest.approx(0.5)
