import numpy as np

from hivemall_tpu.frame.evaluation import (auc, average_precision, f1score,
                                           hitrate, logloss, mae, mrr, mse,
                                           ndcg, precision_at, r2, recall_at,
                                           rmse)


def test_auc_perfect_and_random():
    y = np.array([1, 1, 0, 0])
    assert auc(y, np.array([0.9, 0.8, 0.2, 0.1])) == 1.0
    assert auc(y, np.array([0.1, 0.2, 0.8, 0.9])) == 0.0
    assert auc(y, np.array([0.5, 0.5, 0.5, 0.5])) == 0.5  # ties -> midrank


def test_auc_pm1_labels():
    y = np.array([1, -1, 1, -1])
    s = np.array([0.7, 0.3, 0.6, 0.4])
    assert auc(y, s) == 1.0


def test_logloss_known():
    y = np.array([1, 0])
    p = np.array([0.8, 0.2])
    expect = -(np.log(0.8) + np.log(0.8)) / 2
    assert abs(logloss(y, p) - expect) < 1e-12


def test_f1():
    a = np.array([1, 1, 0, 0])
    p = np.array([1, 0, 1, 0])
    assert abs(f1score(a, p) - 0.5) < 1e-12


def test_regression_metrics():
    a = np.array([1.0, 2.0, 3.0])
    p = np.array([1.0, 2.0, 4.0])
    assert abs(mae(a, p) - 1 / 3) < 1e-12
    assert abs(mse(a, p) - 1 / 3) < 1e-12
    assert abs(rmse(a, p) - np.sqrt(1 / 3)) < 1e-12
    assert r2(a, a) == 1.0
    assert r2(a, p) < 1.0


def test_ranking_metrics():
    rec = ["a", "b", "c", "d"]
    truth = ["b", "d", "e"]
    assert abs(precision_at(rec, truth, 2) - 0.5) < 1e-12
    assert abs(recall_at(rec, truth, 4) - 2 / 3) < 1e-12
    assert hitrate(rec, truth, 1) == 0.0
    assert hitrate(rec, truth, 2) == 1.0
    assert abs(mrr(rec, truth) - 0.5) < 1e-12
    ap = average_precision(rec, truth)
    assert abs(ap - (0.5 + 0.5) / 3) < 1e-12


def test_ndcg_binary_and_graded():
    assert ndcg(["a", "b"], ["a", "b"]) == 1.0
    assert ndcg(["b", "a"], {"a": 3.0, "b": 1.0}, 2) < 1.0
    assert ndcg(["a", "b"], {"a": 3.0, "b": 1.0}, 2) == 1.0
    assert ndcg([], ["a"]) == 0.0
