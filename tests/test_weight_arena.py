"""Weight arena + quantized scoring + router result cache (ISSUE 15,
docs/PERFORMANCE.md "Weight arena + quantized scoring"): the mmap'd
multi-precision serving sidecar, its numpy scorer twins and error
bounds, the engine's zero-copy load path (quantization OFF bit-matches
the pre-arena path), the promotion gate's quantized-candidate
guardrail, and the router's invalidate-on-reload result cache."""

import json
import os
import threading

import numpy as np
import pytest

from hivemall_tpu.io import weight_arena as wa
from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.io.shard_cache import CacheInvalid
from hivemall_tpu.io.sparse import SparseBatch, SparseDataset

OPTS = "-dims 4096 -loss logloss -opt adagrad -mini_batch 64"


def _bundle_path(tmp, trainer):
    return os.path.join(str(tmp),
                        f"{trainer.NAME}-step{trainer._t:010d}.npz")


def _save(tmp, trainer):
    p = _bundle_path(tmp, trainer)
    trainer.save_bundle(p)
    return p


@pytest.fixture(scope="module")
def linear_setup(tmp_path_factory):
    from hivemall_tpu.models.linear import GeneralClassifier
    tmp = tmp_path_factory.mktemp("arena_linear")
    ds, _ = synthetic_classification(256, 80, seed=5)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    path = _save(tmp, t)
    arena = wa.open_arena(wa.publish_arena(path, t))
    return {"tmp": tmp, "ds": ds, "trainer": t, "path": path,
            "arena": arena}


def _ffm_dataset(n=256, L=8, F=8, dims=4000, seed=9):
    rng = np.random.default_rng(seed)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    return SparseDataset(idx.ravel(),
                         np.arange(0, n * L + 1, L, dtype=np.int64),
                         rng.uniform(0.5, 1.5, n * L).astype(np.float32),
                         lab, fld.ravel())


def _rand_batch(rng, B, L, dims=4000, F=None):
    idx = rng.integers(1, dims, (B, L)).astype(np.int32)
    val = rng.uniform(0.2, 1.5, (B, L)).astype(np.float32)
    fld = (rng.integers(0, F, (B, L)).astype(np.int32)
           if F is not None else None)
    return SparseBatch(idx, val, np.zeros(B, np.float32), fld)


# --- container / quantization ------------------------------------------------

def test_publish_open_roundtrip(linear_setup):
    a = linear_setup["arena"]
    assert a.family == "linear" and a.classification
    assert a.trainer_name == "train_classifier"
    assert a.step == linear_setup["trainer"]._t
    assert set(a.precisions) == {"f32", "bf16", "int8"}
    assert a.mapped_bytes > 0
    assert a.matches_bundle(linear_setup["path"])


def test_stale_arena_detected(tmp_path):
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(128, 60, seed=6)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    p = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p)
    ap = wa.publish_arena(p, t)
    # bundle rewritten in place (newer training state, same path):
    # the arena's recorded source digest no longer matches
    t.fit(ds)
    t._t -= 1   # keep the filename/step identical
    t.save_bundle(p)
    assert not wa.open_arena(ap).matches_bundle(p)


def test_corrupt_arena_refused(linear_setup, tmp_path):
    import shutil
    src = wa.arena_path(linear_setup["path"])
    bad = str(tmp_path / "bad.arena")
    shutil.copy(src, bad)
    with open(bad, "r+b") as f:
        f.seek(-16, os.SEEK_END)
        f.write(b"\xff" * 8)
    with pytest.raises(CacheInvalid):
        wa.open_arena(bad)


def test_quantize_int8_contract():
    rng = np.random.default_rng(0)
    a = rng.normal(size=1000).astype(np.float32) * 3.0
    q, scale = wa.quantize_int8(a)
    assert q.dtype == np.int8
    assert np.isclose(scale, np.abs(a).max() / 127.0)
    # round-to-nearest: per-weight error <= scale / 2
    assert np.abs(q.astype(np.float32) * scale - a).max() <= scale / 2 + 1e-7
    qz, sz = wa.quantize_int8(np.zeros(4, np.float32))
    assert sz == 1.0 and not qz.any()


def test_bf16_shift_matches_mldtypes():
    import ml_dtypes
    rng = np.random.default_rng(1)
    a = (rng.normal(size=512).astype(np.float32) *
         10.0 ** rng.integers(-6, 6, 512))
    bits = wa._to_bf16_bits(a)
    via_shift = wa._bf16_bits_to_f32(bits)
    via_lib = bits.view(ml_dtypes.bfloat16).astype(np.float32)
    assert np.array_equal(via_shift, via_lib)


def test_row_hash_matches_jitted():
    import jax.numpy as jnp
    from hivemall_tpu.ops.fm import ffm_row_hash
    rng = np.random.default_rng(2)
    idx = rng.integers(0, 1 << 31, (16, 8)).astype(np.int32)
    for Mr in (256, 4096):
        ref = np.asarray(ffm_row_hash(jnp.asarray(idx), Mr))
        assert np.array_equal(wa._row_hash_np(idx, Mr), ref)


# --- error-bound property tests: every family, every (B, L) bucket ----------

def _family_cases(tmp_path_factory):
    from hivemall_tpu.models.fm import FFMTrainer, FMTrainer
    from hivemall_tpu.models.linear import GeneralClassifier
    tmp = tmp_path_factory.mktemp("arena_families")
    ds, _ = synthetic_classification(256, 80, seed=5)
    dsf = _ffm_dataset()
    out = []
    for name, cls, opts, data, F in (
            ("linear", GeneralClassifier, OPTS, ds, None),
            ("fm_fused", FMTrainer,
             "-dims 4000 -factors 4 -classification -opt adagrad",
             ds, None),
            ("ffm_joint", FFMTrainer,
             "-dims 4096 -factors 2 -fields 8 -classification",
             dsf, 8),
            ("ffm_dense", FFMTrainer,
             "-dims 500 -factors 2 -fields 8 -classification "
             "-ffm_table dense", dsf, 8)):
        t = cls(opts)
        t.fit(data)
        p = os.path.join(str(tmp), f"{name}-{t.NAME}.npz")
        t.save_bundle(p)
        a = wa.open_arena(wa.publish_arena(p, t))
        dims = 500 if name == "ffm_dense" else 4000
        out.append((name, t, a, F, dims))
    return out


@pytest.fixture(scope="module")
def family_cases(tmp_path_factory):
    return _family_cases(tmp_path_factory)


def test_quant_error_within_documented_bound(family_cases):
    """int8/bf16 margins within score_error_bound of f32, and the f32
    arena tier numerically equal to the trainer's own margin — across
    every (B, L) serve bucket shape and every scorer family."""
    rng = np.random.default_rng(3)
    for name, t, a, F, dims in family_cases:
        margin_ref = t._make_margin_fn()
        # FFM's pairwise [B,L,L,K] reference cube is the expensive leg;
        # the L=64 column only needs one B to pin the wide bucket
        shapes = ([(1, 8), (8, 16), (64, 16), (8, 64)]
                  if name.startswith("ffm")
                  else [(B, L) for B in (1, 8, 64) for L in (8, 16, 64)])
        for B, L in shapes:
                b = _rand_batch(rng, B, L, dims=dims, F=F)
                ref = np.asarray(margin_ref(b), np.float32)
                for prec in ("f32", "bf16", "int8"):
                    m = a.margin_fn(prec)(b)
                    bound = wa.score_error_bound(a, prec, b) \
                        + 1e-4 + 1e-5 * np.abs(ref)
                    err = np.abs(m - ref)
                    assert (err <= bound).all(), \
                        (name, prec, B, L, float(err.max()),
                         float(bound.min()))
                    if prec == "f32":
                        assert np.allclose(m, ref, rtol=1e-5,
                                           atol=2e-5), (name, B, L)


def test_f32_bound_is_zero_quant_bounds_positive(linear_setup):
    rng = np.random.default_rng(4)
    b = _rand_batch(rng, 8, 16)
    a = linear_setup["arena"]
    assert not wa.score_error_bound(a, "f32", b).any()
    assert (wa.score_error_bound(a, "int8", b) > 0).all()
    assert (wa.score_error_bound(a, "bf16", b) >= 0).all()


def test_scorer_probability_space(linear_setup):
    """Classification arenas emit probabilities through the family's
    own sigmoid form — f32 tier matches make_scorer exactly-ish."""
    rng = np.random.default_rng(5)
    b = _rand_batch(rng, 8, 16)
    ref = np.asarray(linear_setup["trainer"].make_scorer()(b))
    got = linear_setup["arena"].scorer("f32")(b)
    assert got.dtype == np.float32
    assert ((got >= 0) & (got <= 1)).all()
    assert np.allclose(got, ref, atol=2e-6)


def test_oob_feature_id_clamps_like_xla(linear_setup):
    """A raw integer feature id past dims must degrade like the jitted
    gather (clamp), never crash the replica."""
    b = SparseBatch(np.array([[999_999_999, 3]], np.int32),
                    np.ones((1, 2), np.float32), np.zeros(1, np.float32))
    for prec in ("f32", "bf16", "int8"):
        assert np.isfinite(linear_setup["arena"].margin_fn(prec)(b)).all()


def test_ffm_parts_unsupported(tmp_path):
    from hivemall_tpu.models.fm import FFMTrainer
    from hivemall_tpu.ops.fm_pallas import parts_supported
    if not parts_supported(8, 2, "adagrad", np.float32):
        pytest.skip("parts layout unsupported on this backend")
    t = FFMTrainer("-dims 4096 -factors 2 -fields 8 -classification "
                   "-ffm_table parts")
    t.fit(_ffm_dataset())
    with pytest.raises(wa.ArenaUnsupported):
        t.serving_tables()


# --- parse-only facade -------------------------------------------------------

def test_make_parser_hashes_identically():
    from hivemall_tpu.models.fm import FFMTrainer
    from hivemall_tpu.models.linear import GeneralClassifier
    full = GeneralClassifier(OPTS)
    parser = GeneralClassifier.make_parser(OPTS)
    row = ["cat:1.5", "7:2.0", "other:1"]
    for a, b in zip(full._parse_row(row), parser._parse_row(row)):
        assert np.array_equal(a, b)
    assert not hasattr(parser, "w"), "parser must not allocate tables"
    fopts = "-dims 4096 -factors 2 -fields 8"
    ffull = FFMTrainer(fopts)
    fparser = FFMTrainer.make_parser(fopts)
    frow = ["3:12:1.5", "f7:abc:2.0"]
    for a, b in zip(ffull._parse_row(frow), fparser._parse_row(frow)):
        assert np.array_equal(a, b)
    assert not hasattr(fparser, "params")


# --- engine integration ------------------------------------------------------

def _rows(ds, n=8):
    out = []
    for i in range(n):
        idx, val = ds.row(i)
        out.append([f"{int(a)}:{float(v)!r}" for a, v in zip(idx, val)])
    return out


@pytest.fixture(scope="module")
def fitted(tmp_path_factory):
    """One fitted trainer shared by the engine tests (each test saves
    its own bundle copy into its own tmp dir — fitting dominates)."""
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(128, 60, seed=8)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    return t, ds


def test_engine_default_bitmatches_prearena_path(tmp_path, fitted):
    """Quantization OFF == today's path: bit-identical scores to
    predict_proba, no arena file created, no arena mapped."""
    from hivemall_tpu.serve.engine import PredictEngine
    t, ds = fitted
    p = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p)
    e = PredictEngine("train_classifier", OPTS, bundle=p,
                      max_batch=16, warmup_len=ds.max_row_len)
    try:
        got = e.predict_rows([e.parse(r) for r in _rows(ds)])
        ref = np.asarray(t.predict_proba(ds)[:8], np.float32)
        assert np.array_equal(got, ref)
        assert not os.path.exists(wa.arena_path(p))
        sec = e.obs_section()
        assert sec["arena"] == {"active": False, "mode": "auto",
                                "mapped_bytes": 0, "loads": 0,
                                "publishes": 0, "fallbacks": 0}
        assert sec["precision"] == "f32"
        assert sec["host_rss_bytes"] is None or sec["host_rss_bytes"] > 0
    finally:
        e.close()


def test_engine_quantized_serves_from_arena(tmp_path, fitted):
    from hivemall_tpu.serve.engine import PredictEngine
    t, ds = fitted
    p = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p)
    e = PredictEngine("train_classifier", OPTS, bundle=p,
                      precision="int8", max_batch=16,
                      warmup_len=ds.max_row_len)
    try:
        # no sidecar existed: the engine published one, then mapped it
        assert e.arena_publishes == 1 and e.arena_loads == 1
        assert os.path.exists(wa.arena_path(p))
        assert e.arena_mapped_bytes > 0
        got = e.predict_rows([e.parse(r) for r in _rows(ds)])
        ref = np.asarray(t.predict_proba(ds)[:8], np.float64)
        assert np.abs(got - ref).max() < 0.05
        # the serving trainer is the parse-only facade, not a full model
        assert not hasattr(e._model.trainer, "w")
        assert e._model.arena is not None
    finally:
        e.close()
    # close released the mapping and the obs surface stays sane
    assert e._model is None
    assert e.obs_section()["arena"]["active"] is False


def test_engine_second_replica_maps_without_publishing(tmp_path, fitted):
    from hivemall_tpu.serve.engine import PredictEngine
    t, ds = fitted
    p = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p)
    wa.publish_arena(p, t)
    e = PredictEngine("train_classifier", OPTS, bundle=p,
                      precision="bf16", max_batch=16,
                      warmup_len=ds.max_row_len)
    try:
        assert e.arena_publishes == 0 and e.arena_loads == 1
    finally:
        e.close()


def test_engine_partial_precision_arena_republished(tmp_path, fitted):
    """A digest-valid sidecar MISSING the requested tier must read as a
    miss (republish with every tier), not wedge reloads on KeyError."""
    from hivemall_tpu.serve.engine import PredictEngine
    t, ds = fitted
    p = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p)
    wa.publish_arena(p, t, precisions=("f32", "bf16"))
    e = PredictEngine("train_classifier", OPTS, bundle=p,
                      precision="int8", max_batch=16,
                      warmup_len=ds.max_row_len)
    try:
        assert e.arena_publishes == 1      # republished with all tiers
        assert "int8" in e._model.arena.precisions
        assert np.isfinite(
            e.predict_rows([e.parse(r) for r in _rows(ds, 2)])).all()
    finally:
        e.close()


def test_engine_force_f32_degrades_on_publish_failure(tmp_path, fitted,
                                                      monkeypatch):
    """--serve-arena force against a read-only model dir (no sidecar):
    the replica holds a servable trainer — it must degrade to the
    bundle path, never die on the publish error. (Simulated by patching
    publish_arena: chmod can't make a dir read-only for root.)"""
    import hivemall_tpu.io.weight_arena as wam
    from hivemall_tpu.serve.engine import PredictEngine
    t, ds = fitted
    p = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p)

    def boom(*a, **kw):
        raise OSError("read-only file system (simulated)")

    monkeypatch.setattr(wam, "publish_arena", boom)
    e = PredictEngine("train_classifier", OPTS, bundle=p,
                      arena="force", max_batch=16,
                      warmup_len=ds.max_row_len)
    try:
        assert e.arena_fallbacks == 1 and e.arena_loads == 0
        assert "publish" in (e.last_reload_error or "")
        got = e.predict_rows([e.parse(r) for r in _rows(ds)])
        assert np.array_equal(
            got, np.asarray(t.predict_proba(ds)[:8], np.float32))
    finally:
        e.close()
    # quantized precision has no bundle fallback: it must raise
    with pytest.raises(OSError):
        PredictEngine("train_classifier", OPTS, bundle=p,
                      precision="int8", max_batch=16,
                      warmup_len=ds.max_row_len)


def test_engine_hot_reload_through_arena(tmp_path):
    from hivemall_tpu.models.linear import GeneralClassifier
    from hivemall_tpu.serve.engine import PredictEngine
    ds, _ = synthetic_classification(128, 60, seed=8)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    p1 = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p1)
    e = PredictEngine("train_classifier", OPTS,
                      checkpoint_dir=str(tmp_path), precision="int8",
                      max_batch=16, warmup_len=ds.max_row_len)
    try:
        step1 = e.model_step
        t.fit(ds)
        p2 = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
        t.save_bundle(p2)
        wa.publish_arena(p2, t)
        assert e.poll() is True
        assert e.model_step == t._t != step1
        assert e.arena_loads == 2 and e.arena_publishes == 1
        ref = np.asarray(t.predict_proba(ds)[:8], np.float64)
        got = e.predict_rows([e.parse(r) for r in _rows(ds)])
        assert np.abs(got - ref).max() < 0.05
    finally:
        e.close()


def test_engine_option_validation():
    from hivemall_tpu.serve.engine import PredictEngine
    with pytest.raises(ValueError, match="precision"):
        PredictEngine("train_classifier", OPTS, bundle="x.npz",
                      precision="fp4")
    with pytest.raises(ValueError, match="arena"):
        PredictEngine("train_classifier", OPTS, bundle="x.npz",
                      arena="maybe")
    with pytest.raises(ValueError, match="needs the weight"):
        PredictEngine("train_classifier", OPTS, bundle="x.npz",
                      precision="int8", arena="off")


# --- promotion gate: the quantized-candidate guardrail -----------------------

def _outlier_candidate(tmp, ds, bump=10):
    """A candidate whose f32 scores are FINE but whose symmetric int8
    quantization collapses: one giant weight on an index the holdout
    never uses makes the per-table scale so coarse that every real
    weight rounds to zero."""
    import jax.numpy as jnp
    from hivemall_tpu.models.linear import GeneralClassifier
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    w = np.array(t.w, np.float32)        # writable copy
    w[4095] = 1e6                        # holdout ids stay < 4000
    t.w = jnp.asarray(w)
    t._t += bump
    path = _save(tmp, t)
    return t, path


@pytest.fixture()
def gated_dir(tmp_path):
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(256, 80, seed=12,)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    base = _save(tmp_path, t)
    return tmp_path, ds, t, base


def test_gate_scores_quantized_and_publishes(gated_dir):
    from hivemall_tpu.serve.promote import PromotionController, PromotionGate
    tmp, ds, t, base = gated_dir
    gate = PromotionGate("train_classifier", OPTS, holdout=ds,
                         precision="int8")
    report = PromotionController(str(tmp), gate).check_once()
    assert report["verdict"] == "pass", report
    assert report["checks"]["precision"] == "int8"
    assert gate.arena_published >= 1
    assert os.path.exists(wa.arena_path(base))
    assert "arena_published" in gate.counters()


def test_gate_quantized_fails_unsupported_family_without_holdout(tmp_path):
    """A quantized gate with NO validation data at all must still fail
    a candidate whose family has no arena mapping — passing it would
    wedge every quantized replica on reload (review-caught edge)."""
    from hivemall_tpu.models.fm import FFMTrainer
    from hivemall_tpu.ops.fm_pallas import parts_supported
    from hivemall_tpu.serve.promote import PromotionGate
    if not parts_supported(8, 2, "adagrad", np.float32):
        pytest.skip("parts layout unsupported on this backend")
    t = FFMTrainer("-dims 4096 -factors 2 -fields 8 -classification "
                   "-ffm_table parts")
    t._t = 1
    p = _save(tmp_path, t)
    report = PromotionGate(
        "train_ffm",
        "-dims 4096 -factors 2 -fields 8 -classification "
        "-ffm_table parts", precision="int8").evaluate(p)
    assert report["verdict"] == "fail"
    assert any("unusable" in r for r in report["reasons"]), report


def test_gate_rejects_over_error_quantized_candidate(gated_dir):
    """The same candidate passes at f32 and FAILS at int8 — proof the
    gate catches quantization error specifically — and the controller
    quarantines it (.rejected marker)."""
    from hivemall_tpu.io.checkpoint import is_rejected, rejected_reason
    from hivemall_tpu.serve.promote import PromotionController, PromotionGate
    tmp, ds, t, base = gated_dir
    # bootstrap-promote the good baseline at int8
    g0 = PromotionGate("train_classifier", OPTS, holdout=ds,
                       precision="int8")
    assert PromotionController(str(tmp), g0).check_once()["verdict"] \
        == "pass"
    _, bad = _outlier_candidate(tmp, ds)
    f32_report = PromotionGate(
        "train_classifier", OPTS, holdout=ds,
        precision="f32").evaluate(bad, base)
    assert f32_report["verdict"] == "pass", f32_report
    gate = PromotionGate("train_classifier", OPTS, holdout=ds,
                         precision="int8")
    report = PromotionController(str(tmp), gate).check_once()
    assert report is not None and report["verdict"] == "fail", report
    assert is_rejected(bad)
    assert rejected_reason(bad)


# --- router result cache -----------------------------------------------------

def test_result_cache_lru_and_invalidate():
    from hivemall_tpu.serve.router import ResultCache
    c = ResultCache(max_entries=2, max_bytes=1 << 20)
    assert c.get(b"a") is None           # miss
    c.put(b"a", b"HTTP/1.1 200 OK\r\n", b"pa")
    c.put(b"b", b"HTTP/1.1 200 OK\r\n", b"pb")
    hit = c.get(b"a")
    assert hit is not None and hit.endswith(b"pa")
    assert b"x-hivemall-cache: hit" in hit
    c.put(b"c", b"HTTP/1.1 200 OK\r\n", b"pc")   # evicts LRU (b)
    assert c.get(b"b") is None
    assert c.get(b"a") is not None and c.get(b"c") is not None
    st = c.stats()
    assert st["entries"] == 2 and st["hits"] == 3 and st["misses"] == 2
    c.invalidate()
    assert c.get(b"a") is None
    assert c.stats()["invalidations"] == 1 and c.stats()["version"] == 1
    c.bypass = True
    c.put(b"d", b"H", b"p")
    assert c.stats()["entries"] == 0     # bypass: nothing cached


def test_result_cache_version_guard_drops_stale_put():
    """A forward in flight across invalidate() carries the PRE-reload
    model's scores — put() must drop it (the review-caught race)."""
    from hivemall_tpu.serve.router import ResultCache
    c = ResultCache(max_entries=8)
    v = c.version                        # snapshot before "forwarding"
    c.invalidate()                       # model changed mid-flight
    c.put(b"a", b"HTTP/1.1 200 OK\r\n", b"stale", version=v)
    assert c.get(b"a") is None and c.stats()["entries"] == 0
    c.put(b"a", b"HTTP/1.1 200 OK\r\n", b"fresh", version=c.version)
    assert c.get(b"a") is not None


def test_result_cache_strips_per_request_headers():
    """A hit must not replay another request's trace id or the original
    forward's hop timing breakdown."""
    from hivemall_tpu.serve.router import ResultCache
    c = ResultCache(max_entries=8)
    head = (b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: application/json\r\n"
            b"x-hivemall-trace: someone-elses-id\r\n"
            b"x-hivemall-hop: parse=1,total=2\r\n"
            b"x-hivemall-hop-router: relay=1,total=3\r\n")
    c.put(b"a", head, b"p")
    hit = c.get(b"a")
    assert b"x-hivemall-trace" not in hit
    assert b"x-hivemall-hop" not in hit
    assert b"Content-Type: application/json" in hit
    assert b"x-hivemall-cache: hit" in hit


def test_result_cache_byte_bound():
    from hivemall_tpu.serve.router import ResultCache
    c = ResultCache(max_entries=100, max_bytes=64)
    c.put(b"a", b"h" * 30, b"p" * 30)
    c.put(b"b", b"h" * 30, b"p" * 30)
    assert c.stats()["bytes"] <= 64 and c.stats()["entries"] == 1


@pytest.fixture()
def router_with_replica(tmp_path, fitted):
    """A real PredictServer registered directly as a router replica —
    the cache integration surface without spawning a fleet."""
    from hivemall_tpu.serve.engine import PredictEngine
    from hivemall_tpu.serve.http import PredictServer
    from hivemall_tpu.serve.router import RouterServer
    t, ds = fitted
    p = os.path.join(str(tmp_path), f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(p)
    engine = PredictEngine("train_classifier", OPTS, bundle=p,
                           max_batch=16, warmup_len=ds.max_row_len)
    srv = PredictServer(engine, watch=False, slo=False).start()
    router = RouterServer(result_cache_entries=64).start()
    router.add_replica("r0", "127.0.0.1", srv.port, ready=True)
    yield router, srv, ds
    router.stop()
    srv.stop()


def test_router_cache_end_to_end(router_with_replica):
    from hivemall_tpu.serve.http import KeepAliveClient
    router, srv, ds = router_with_replica
    cli = KeepAliveClient("127.0.0.1", router.port)
    try:
        body = {"rows": _rows(ds, 2)}
        code1, r1 = cli.post_json("/predict", body)
        assert code1 == 200
        assert "x-hivemall-cache" not in cli.last_headers
        code2, r2 = cli.post_json("/predict", body)
        assert code2 == 200 and r2["scores"] == r1["scores"]
        assert cli.last_headers.get("x-hivemall-cache") == "hit"
        st = router.result_cache.stats()
        assert st["hits"] == 1 and st["entries"] >= 1
        # a model change invalidates: the next identical body forwards
        router.invalidate_result_cache()
        code3, _ = cli.post_json("/predict", body)
        assert code3 == 200
        assert "x-hivemall-cache" not in cli.last_headers
        # router stats + fleet snapshot carry the cache counters and
        # the memory gauges
        assert router.stats()["result_cache"]["invalidations"] == 1
        snap = router.fleet_snapshot()["fleet"]
        agg = snap["aggregate"]
        assert agg["host_rss_bytes"] > 0
        assert "arena_mapped_bytes" in agg \
            and "arena_mapped_bytes_unique" in agg
        sec = snap["replicas"]["r0"]
        assert sec["host_rss_bytes"] > 0 and "arena" in sec
    finally:
        cli.close()


def test_router_cache_disabled_stub():
    from hivemall_tpu.serve.router import RouterServer, _CACHE_STUB
    r = RouterServer()
    try:
        st = r.stats()["result_cache"]
        assert st == _CACHE_STUB
        r.invalidate_result_cache()      # no-op, must not raise
        r.set_result_cache_bypass(True)
    finally:
        r.stop()


# --- retention ---------------------------------------------------------------

def test_prune_removes_arena_sidecar_keeps_pinned(tmp_path):
    from hivemall_tpu.io.checkpoint import (CheckpointManager,
                                            promote_bundle)
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(128, 60, seed=8)
    t = GeneralClassifier(OPTS)
    mgr = CheckpointManager(str(tmp_path), t.NAME, keep=2)
    paths = []
    for _ in range(4):
        t.fit(ds)
        paths.append(mgr.save(t))
        wa.publish_arena(paths[-1], t)
    # keep=2: the two oldest bundles were pruned WITH their arenas
    assert not os.path.exists(paths[0])
    assert not os.path.exists(wa.arena_path(paths[0]))
    assert os.path.exists(wa.arena_path(paths[-1]))
    # a pointer-pinned bundle keeps its arena through further churn
    promote_bundle(str(tmp_path), paths[2])
    for _ in range(3):
        t.fit(ds)
        p = mgr.save(t)
        wa.publish_arena(p, t)
    assert os.path.exists(paths[2])
    assert os.path.exists(wa.arena_path(paths[2]))


def test_host_rss_bytes_reads():
    rss = wa.host_rss_bytes()
    if os.path.exists("/proc/self/statm"):
        assert rss is not None and rss > (1 << 20)
