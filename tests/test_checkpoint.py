"""Checkpoint/resume bundles (SURVEY.md §6): resumed == continuous."""

import numpy as np
import pytest

from hivemall_tpu.models.fm import FMTrainer
from hivemall_tpu.models.linear import GeneralClassifier, GeneralRegressor


def _rows(n, d=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = np.where(X[:, 0] - 0.3 * X[:, 1] > 0, 1, -1)
    feats = [[f"f{j}:{X[i, j]:.5f}" for j in range(d)] for i in range(n)]
    return feats, y


OPTS = "-opt adagrad -loss logloss -mini_batch 8 -dims 4096"


def test_resume_equals_continuous(tmp_path):
    feats, y = _rows(96)
    cont = GeneralClassifier(OPTS)
    for f, lab in zip(feats, y):
        cont.process(f, lab)
    cont_rows = dict(cont.close())

    first = GeneralClassifier(OPTS)
    for f, lab in zip(feats[:48], y[:48]):
        first.process(f, lab)
    first._flush()
    p = tmp_path / "ck.npz"
    first.save_bundle(str(p))

    second = GeneralClassifier(OPTS)
    second.load_bundle(str(p))
    assert second._t == first._t and second._examples == 48
    for f, lab in zip(feats[48:], y[48:]):
        second.process(f, lab)
    res_rows = dict(second.close())

    assert set(res_rows) == set(cont_rows)
    for k in cont_rows:
        np.testing.assert_allclose(res_rows[k], cont_rows[k],
                                   rtol=1e-6, atol=1e-7)


def test_bundle_keeps_optimizer_state(tmp_path):
    """AdaGrad accumulators survive the roundtrip (what -loadmodel loses)."""
    feats, y = _rows(32)
    tr = GeneralClassifier(OPTS)
    for f, lab in zip(feats, y):
        tr.process(f, lab)
    tr._flush()
    p = tmp_path / "ck.npz"
    tr.save_bundle(str(p))
    fresh = GeneralClassifier(OPTS)
    fresh.load_bundle(str(p))
    ref = tr._checkpoint_arrays()
    got = fresh._checkpoint_arrays()
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=1e-6)


def test_fm_bundle_roundtrip(tmp_path):
    feats, y = _rows(40)
    tr = FMTrainer("-factors 4 -mini_batch 8 -dims 2048 -classification")
    for f, lab in zip(feats, y):
        tr.process(f, lab)
    tr._flush()
    p = tmp_path / "fm.npz"
    tr.save_bundle(str(p))
    fresh = FMTrainer("-factors 4 -mini_batch 8 -dims 2048 -classification")
    fresh.load_bundle(str(p))
    np.testing.assert_allclose(np.asarray(fresh.params["T"], np.float32),
                               np.asarray(tr.params["T"], np.float32))


def test_rda_resume_keeps_dual_accumulators(tmp_path):
    """RDA recomputes w from u/gg each step — they must survive the bundle."""
    from hivemall_tpu.models.classifier import AdaGradRDATrainer
    feats, y = _rows(96)
    opts = "-mini_batch 8 -dims 4096"
    cont = AdaGradRDATrainer(opts)
    for f, lab in zip(feats, y):
        cont.process(f, lab)
    cont_rows = dict(cont.close())

    first = AdaGradRDATrainer(opts)
    for f, lab in zip(feats[:48], y[:48]):
        first.process(f, lab)
    first._flush()
    p = tmp_path / "rda.npz"
    first.save_bundle(str(p))
    second = AdaGradRDATrainer(opts)
    second.load_bundle(str(p))
    assert float(np.abs(np.asarray(second.gg)).sum()) > 0
    for f, lab in zip(feats[48:], y[48:]):
        second.process(f, lab)
    res_rows = dict(second.close())
    assert set(res_rows) == set(cont_rows)
    for k in cont_rows:
        np.testing.assert_allclose(res_rows[k], cont_rows[k],
                                   rtol=1e-6, atol=1e-7)


def test_save_bundle_atomic_leaves_no_litter(tmp_path):
    """tmp -> fsync -> os.replace: after a save (including overwriting an
    existing bundle) the directory holds exactly the bundle, no tmp files,
    and the result round-trips."""
    import os
    feats, y = _rows(24)
    tr = GeneralClassifier(OPTS)
    for f, lab in zip(feats, y):
        tr.process(f, lab)
    tr._flush()
    p = tmp_path / "ck.npz"
    tr.save_bundle(str(p))
    tr.save_bundle(str(p))              # overwrite path also atomic
    assert os.listdir(tmp_path) == ["ck.npz"]
    fresh = GeneralClassifier(OPTS)
    fresh.load_bundle(str(p))
    assert fresh._t == tr._t


def test_bundle_digest_detects_tamper(tmp_path):
    """The format-2 manifest digest catches a bit-flipped leaf that the
    zip container itself would happily return."""
    import json
    feats, y = _rows(24)
    tr = GeneralClassifier(OPTS)
    for f, lab in zip(feats, y):
        tr.process(f, lab)
    tr._flush()
    p = tmp_path / "ck.npz"
    tr.save_bundle(str(p))
    with np.load(str(p), allow_pickle=False) as z:
        data = {k: z[k] for k in z.files}
    meta = json.loads(str(data["__meta__"]))
    assert meta["format"] == 2 and "digest" in meta
    data["leaf_0"] = data["leaf_0"] + 1          # tamper one leaf
    np.savez(str(p), **data)
    fresh = GeneralClassifier(OPTS)
    with pytest.raises(ValueError, match="digest mismatch"):
        fresh.load_bundle(str(p))


def test_checkpoint_manager_retention(tmp_path):
    """-checkpoint_keep k: only the k newest step bundles survive, and
    resume() restores the newest."""
    from hivemall_tpu.io.checkpoint import list_bundles
    from hivemall_tpu.io.libsvm import synthetic_classification
    ds, _ = synthetic_classification(192, 8, seed=7)
    ckdir = str(tmp_path / "ck")
    opts = (f"{OPTS} -steps_per_dispatch 1 -checkpoint_dir {ckdir} "
            f"-checkpoint_every 2 -checkpoint_keep 2")
    tr = GeneralClassifier(opts)
    tr.fit_stream(ds.batches(16, shuffle=False))     # 12 batches
    bundles = list_bundles(ckdir, tr.NAME)
    assert len(bundles) == 2                         # retention enforced
    r = GeneralClassifier(opts)
    assert r.resume()
    assert r._t == tr._t                             # newest == final state


def test_prune_spares_in_use_bundles(tmp_path):
    """Last-k retention must not GC a bundle a live reader holds open
    (the ``.pin.<pid>`` sidecar a bulk scoring job writes via
    hold_bundle): the held bundle survives pruning past the keep window,
    ages out normally once the hold releases, and a stale pin left by a
    dead holder is swept instead of leaking retention forever."""
    import os
    import subprocess
    import sys
    from hivemall_tpu.io.checkpoint import (CheckpointManager, hold_bundle,
                                            in_use_bundles)

    feats, y = _rows(64)
    tr = GeneralClassifier(OPTS)
    mgr = CheckpointManager(str(tmp_path), tr.NAME, keep=1)

    def advance_and_save(lo, hi):
        for f, lab in zip(feats[lo:hi], y[lo:hi]):
            tr.process(f, lab)
        tr._flush()
        return mgr.save(tr)

    p1 = advance_and_save(0, 16)
    with hold_bundle(p1):
        assert os.path.exists(p1 + f".pin.{os.getpid()}")
        assert in_use_bundles(str(tmp_path)) == {p1}
        p2 = advance_and_save(16, 32)         # prune: p1 pinned, survives
        assert os.path.exists(p1)
        p3 = advance_and_save(32, 48)         # p2 has no pin: pruned
        assert os.path.exists(p1) and os.path.exists(p3)
        assert not os.path.exists(p2)
    assert not os.path.exists(p1 + f".pin.{os.getpid()}")
    p4 = advance_and_save(48, 64)             # hold released: p1 ages out
    assert os.path.exists(p4) and not os.path.exists(p1)

    # a pin whose holder died must be swept, not honored forever
    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    stale = p4 + f".pin.{child.pid}"
    with open(stale, "w") as f:
        f.write('{"pid": %d}' % child.pid)
    assert in_use_bundles(str(tmp_path)) == set()
    assert not os.path.exists(stale)


def test_bundle_rejects_mismatch(tmp_path):
    feats, y = _rows(16)
    tr = GeneralClassifier(OPTS)
    for f, lab in zip(feats, y):
        tr.process(f, lab)
    p = tmp_path / "ck.npz"
    tr.save_bundle(str(p))
    with pytest.raises(ValueError, match="cannot resume"):
        GeneralRegressor(OPTS.replace("logloss", "squaredloss")) \
            .load_bundle(str(p))
    with pytest.raises(ValueError, match="mismatch"):
        GeneralClassifier("-opt adagrad -loss logloss -dims 1024") \
            .load_bundle(str(p))


def test_mf_resume_equals_continuous(tmp_path):
    """Non-LearnerBase trainer (MF AdaGrad) bundles via the same protocol."""
    from hivemall_tpu.models.mf import MFAdaGradTrainer
    rng = np.random.default_rng(5)
    opts = "-factors 4 -users 30 -items 20 -mini_batch 8 -seed 2"
    trips = [(int(rng.integers(30)), int(rng.integers(20)),
              float(rng.normal())) for _ in range(80)]

    cont = MFAdaGradTrainer(opts)
    for u, i, r in trips:
        cont.process(u, i, r)
    cont._flush()
    ref = np.asarray(cont.params["P"], np.float32)

    first = MFAdaGradTrainer(opts)
    for u, i, r in trips[:40]:
        first.process(u, i, r)
    first._flush()
    p = tmp_path / "mf.npz"
    first.save_bundle(str(p))
    second = MFAdaGradTrainer(opts)
    second.load_bundle(str(p))
    assert second._t == first._t
    for u, i, r in trips[40:]:
        second.process(u, i, r)
    second._flush()
    np.testing.assert_allclose(np.asarray(second.params["P"], np.float32),
                               ref, rtol=1e-6, atol=1e-7)


def test_per_epoch_auto_checkpoint(tmp_path, monkeypatch):
    """HIVEMALL_TPU_CHECKPOINT_DIR => one bundle per fit() epoch (§6)."""
    import os
    from hivemall_tpu.io.libsvm import synthetic_classification
    monkeypatch.setenv("HIVEMALL_TPU_CHECKPOINT_DIR", str(tmp_path))
    ds, _ = synthetic_classification(64, 16, seed=9)
    tr = GeneralClassifier("-dims 128 -mini_batch 16 -iters 3")
    tr.fit(ds)
    files = sorted(os.listdir(tmp_path))
    assert files == [f"train_classifier-ep{i}.npz" for i in (1, 2, 3)]
    resumed = GeneralClassifier("-dims 128 -mini_batch 16 -iters 3")
    resumed.load_bundle(str(tmp_path / files[-1]))
    assert resumed._t == tr._t


def test_lda_bundle_resume(tmp_path):
    """Topic-model bundles: lambda matrix + hashed vocab names survive."""
    from hivemall_tpu.models.topicmodel import LDATrainer
    docs_a = [["apple", "banana", "fruit"] * 4 for _ in range(10)]
    docs_b = [["stock", "market", "trade"] * 4 for _ in range(10)]
    opts = "-topics 2 -vocab 1024 -mini_batch 4"
    tr = LDATrainer(opts)
    for d in docs_a + docs_b:
        tr.process(d)
    tr._flush()
    p = tmp_path / "lda.npz"
    tr.save_bundle(str(p))
    fresh = LDATrainer(opts)
    fresh.load_bundle(str(p))
    np.testing.assert_allclose(np.asarray(fresh.lam), np.asarray(tr.lam))
    assert fresh._vocab_names == tr._vocab_names
    assert fresh._t == tr._t
    # restored model assigns the same topics
    np.testing.assert_allclose(fresh.transform(["apple", "banana"]),
                               tr.transform(["apple", "banana"]), rtol=1e-6)


def test_multiclass_bundle_resume(tmp_path):
    """Multiclass bundles keep the class-row map with label types intact."""
    from hivemall_tpu.models.multiclass import MulticlassPerceptronTrainer
    rng = np.random.default_rng(8)
    opts = "-classes 3 -dims 1024 -mini_batch 8"
    tr = MulticlassPerceptronTrainer(opts)
    for _ in range(60):
        x = rng.normal(size=3)
        cls = int(np.argmax(x))
        tr.process([f"f{j}:{x[j]:.4f}" for j in range(3)], cls)
    tr._flush()
    p = tmp_path / "mc.npz"
    tr.save_bundle(str(p))
    fresh = MulticlassPerceptronTrainer(opts)
    fresh.load_bundle(str(p))
    assert fresh._labels == tr._labels
    assert all(isinstance(k, int) for k in fresh._labels)
    np.testing.assert_allclose(np.asarray(fresh.W), np.asarray(tr.W))


def test_multiclass_bundle_bool_labels(tmp_path):
    from hivemall_tpu.models.multiclass import MulticlassPerceptronTrainer
    opts = "-classes 2 -dims 256 -mini_batch 4"
    tr = MulticlassPerceptronTrainer(opts)
    for i in range(8):
        tr.process([f"f{i % 3}:1.0"], bool(i % 2))
    tr._flush()
    p = tmp_path / "b.npz"
    tr.save_bundle(str(p))
    fresh = MulticlassPerceptronTrainer(opts)
    fresh.load_bundle(str(p))
    assert fresh._labels == tr._labels
    assert all(isinstance(k, bool) for k in fresh._labels)
