"""Training-side deep profiling (obs.devprof, docs/OBSERVABILITY.md
"Training profiling"): compile/retrace telemetry, the no-retrace
sentinel, device-memory accounting, drift watches, and the devprof
surface on /snapshot + /metrics."""

import io
import json

import numpy as np
import pytest

import hivemall_tpu.utils.metrics as M
from hivemall_tpu.io.sparse import SparseDataset
from hivemall_tpu.models.linear import GeneralClassifier, _linear_step_cached
from hivemall_tpu.obs.devprof import (DriftWatch, devprof_stub, get_devprof,
                                      instrument_factory)
from hivemall_tpu.obs.registry import registry


def _dataset(n=256, L=8, dims=1 << 10, seed=0):
    rng = np.random.default_rng(seed)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    return SparseDataset(idx.ravel(),
                         np.arange(0, n * L + 1, L, dtype=np.int64),
                         np.ones(n * L, np.float32), lab)


@pytest.fixture
def sink_stream():
    """Capture the metrics jsonl into a StringIO for the test's scope."""
    sink = io.StringIO()
    old = M._stream
    M._stream = M.MetricsStream(sink)
    try:
        yield sink
    finally:
        M._stream = old


def _events(sink):
    return [json.loads(line) for line in sink.getvalue().splitlines()
            if line]


# --- factory instrumentation -------------------------------------------------


def test_instrument_factory_counts_builds_only_on_miss():
    from functools import lru_cache

    dp = get_devprof()

    @instrument_factory("testmodel", "step")
    @lru_cache(maxsize=8)
    def factory(a, b):
        return (a, b)

    before = dict(dp.builds.get("testmodel.step") or {"count": 0})
    factory(1, 2)
    factory(1, 2)          # cache hit: no build
    factory(3, 4)          # second distinct config
    b = dp.builds["testmodel.step"]
    assert b["count"] - before["count"] == 2
    assert b["seconds"] >= 0.0
    # the lru surface survives the wrapper (tests/injection paths use it)
    assert factory.cache_info().hits >= 1
    raw = factory
    while hasattr(raw, "__wrapped__"):
        raw = raw.__wrapped__
    assert raw(1, 2) == (1, 2)


def test_shape_bucket_dedup():
    dp = get_devprof()
    n0 = len(dp._buckets)
    dp.note_bucket("test_site", 64, 16)
    dp.note_bucket("test_site", 64, 16)      # dup: no growth
    dp.note_bucket("test_site", 128, 16)
    assert len(dp._buckets) == n0 + 2


# --- no-retrace sentinel -----------------------------------------------------


def test_warmed_epoch_adds_zero_compiles_and_injection_is_caught(
        sink_stream):
    """The acceptance invariant: with the config caches intact a warmed
    epoch (and a duplicate-config trainer) adds ZERO XLA compiles; a
    fresh closure bypassing the factory compiles and is flagged as a
    `retrace` — counter + jsonl event."""
    dp = get_devprof()
    dims, B = 1 << 10, 64
    ds = _dataset(dims=dims)
    opts = f"-dims {dims} -mini_batch {B} -opt adagrad"
    t = GeneralClassifier(opts)
    t.fit(ds, epochs=1, shuffle=False)          # warmup epoch
    dp.arm()
    try:
        c0, r0 = dp.compiles, dp.retraces
        t.fit(ds, epochs=1, shuffle=False)
        assert dp.compiles == c0, "warmed epoch recompiled"
        t2 = GeneralClassifier(opts)            # dup config, caches intact
        t2.fit(ds, epochs=1, shuffle=False)
        assert dp.compiles == c0, "cached duplicate-config recompiled"
        # the disease: a fresh jitted closure instead of the cached step
        raw = _linear_step_cached
        while hasattr(raw, "__wrapped__"):
            raw = raw.__wrapped__
        t3 = GeneralClassifier(opts)
        t3._step = raw("hingeloss", "adagrad", str(t3.opts.eta),
                       float(t3.opts.eta0), t3.opts.total_steps,
                       t3.opts.power_t, str(t3.opts.reg),
                       t3.opts["lambda"], t3.opts.l1_ratio)
        t3.fit(ds, epochs=1, shuffle=False)
        assert dp.compiles > c0 and dp.retraces > r0
        evs = _events(sink_stream)
        retr = [e for e in evs if e["event"] == "retrace"]
        assert retr and retr[0]["seconds"] > 0
    finally:
        dp.disarm()


def test_train_done_auto_arms():
    dp = get_devprof()
    dp.disarm()
    t = GeneralClassifier("-dims 256 -mini_batch 32")
    t.fit(_dataset(n=64, dims=256), epochs=1, shuffle=False)
    assert dp.armed        # one completed fit = warmup over
    dp.disarm()


# --- memory accounting -------------------------------------------------------


def test_sample_memory_gauges():
    dp = get_devprof()
    rec = dp.sample_memory()
    assert set(rec) == {"live_arrays", "live_bytes", "bytes_in_use",
                        "peak_bytes_in_use", "bytes_limit"}
    # a trainer's tables are live jax arrays — the census must see bytes
    t = GeneralClassifier("-dims 4096 -mini_batch 32")
    rec = dp.sample_memory()
    assert rec["live_arrays"] >= 1
    assert rec["live_bytes"] >= 4096 * 4
    assert t is not None


def test_telemetry_cadence_carries_devprof_memory(sink_stream):
    t = GeneralClassifier("-dims 512 -mini_batch 32 -telemetry_every 4")
    t.fit(_dataset(n=256, dims=512), epochs=1, shuffle=False)
    tele = [e for e in _events(sink_stream) if e["event"] == "telemetry"]
    assert tele
    dp_sec = tele[-1]["snapshot"]["devprof"]
    assert dp_sec["memory"]["live_bytes"] > 0
    assert dp_sec["dispatches"] > 0


# --- drift watches -----------------------------------------------------------


def test_drift_watch_flags_step_regression(sink_stream):
    """A sustained 50x step-time regression after a stable warmup must
    cross the self-calibrated threshold and emit the named event."""
    rng = np.random.default_rng(3)
    w = DriftWatch("step_ms", "train_drift", warmup=16)
    for _ in range(64):
        w.update(1.0 + 0.01 * rng.standard_normal())
    assert w.events == 0
    for _ in range(32):
        w.update(50.0 + 0.01 * rng.standard_normal())
    assert w.events >= 1
    evs = [e for e in _events(sink_stream) if e["event"] == "train_drift"]
    assert evs and evs[0]["series"] == "step_ms"
    assert evs[0]["stage"] in ("outlier", "change")


# --- registry + HTTP surface -------------------------------------------------


def test_devprof_section_on_snapshot_and_metrics():
    from hivemall_tpu.obs.http import ObsServer
    import urllib.request

    get_devprof()                       # ensure the live provider is in
    t = GeneralClassifier("-dims 256 -mini_batch 32")
    t.fit(_dataset(n=64, dims=256), epochs=1, shuffle=False)
    srv = ObsServer(0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        snap = json.loads(urllib.request.urlopen(
            base + "/snapshot", timeout=10).read())
        assert "devprof" in snap
        assert snap["devprof"]["compiles"] >= 0
        assert set(devprof_stub()) == set(snap["devprof"])
        text = urllib.request.urlopen(
            base + "/metrics", timeout=10).read().decode()
        assert "hivemall_tpu_devprof_compiles" in text
        assert "hivemall_tpu_devprof_retraces" in text
        assert "hivemall_tpu_devprof_memory_live_bytes" in text
        assert "hivemall_tpu_spans_dropped" in text
    finally:
        srv.stop()


def test_profile_env_routes_through_devprof(tmp_path, monkeypatch,
                                            sink_stream):
    """HIVEMALL_TPU_PROF=<dir> captures a jax.profiler trace of the
    first fit and emits a `profile` event carrying the dir."""
    dp = get_devprof()
    if dp._profiled:
        pytest.skip("a profile was already captured in this process")
    prof_dir = str(tmp_path / "prof")
    monkeypatch.setenv("HIVEMALL_TPU_PROF", prof_dir)
    t = GeneralClassifier("-dims 256 -mini_batch 32")
    t.fit(_dataset(n=64, dims=256), epochs=1, shuffle=False)
    evs = [e for e in _events(sink_stream) if e["event"] == "profile"]
    assert evs and evs[0]["dir"] == prof_dir
    import os
    assert os.path.isdir(prof_dir)


# --- perf-regression gate (bench.py --compare machinery) --------------------


def test_compare_results_gate():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    fresh = {"ffm_e2e": [100.0, 90.0], "ingest": [1000.0, 950.0],
             "serve_qps": [10.0, 9.0]}
    recorded = {"ffm_e2e": [100.0, 100.0], "ingest": [1000.0, 1000.0],
                "serve_qps": [100.0, 100.0], "gone": [5.0, 5.0]}
    # within tolerance: no regression; serve_qps is volatile (never gated)
    regs, lines = bench._compare_results(fresh, recorded, tolerance=0.25)
    assert regs == []
    assert any("volatile" in ln for ln in lines)
    assert any("gone" in ln and "skipped" in ln for ln in lines)
    # a >= tolerance drop on a gated key must flag
    fresh["ffm_e2e"] = [60.0, 60.0]
    regs, _ = bench._compare_results(fresh, recorded, tolerance=0.25)
    assert [r["key"] for r in regs] == ["ffm_e2e"]

    # record round-trip: the v1 schema parses back with the same keys
    rec = {"schema": bench._RECORD_SCHEMA, "chip": {"platform": "cpu"},
           "smoke": True, "results": recorded}
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(rec, f)
        path = f.name
    try:
        loaded = bench._load_bench_record(path)
        assert loaded["results"] == recorded
        assert loaded["platform"] == "cpu" and loaded["smoke"] is True
    finally:
        os.unlink(path)


def test_driver_capture_record_parses():
    """The historical BENCH_r04/r05 driver captures (stdout tail with the
    compact summary line last) must yield per-key results."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    root = os.path.join(os.path.dirname(__file__), "..")
    r05 = bench._load_bench_record(os.path.join(root, "BENCH_r05.json"))
    assert r05 and "ffm_e2e" in r05["results"]
    assert r05["smoke"] is False       # full-shape: never gates smoke runs
    path, newest = bench._newest_bench_record(root)
    assert newest and newest["results"]
