"""Arrow/Parquet/CSV ingest + out-of-core streaming epochs (SURVEY.md §1,
§8 M0; VERDICT r1 missing #1)."""

import numpy as np
import pytest

pytest.importorskip("pyarrow")

from hivemall_tpu.io.arrow import (ParquetStream, read_csv, read_parquet,
                                   table_to_dataset, write_parquet_shards)
from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.io.sparse import SparseDataset
from hivemall_tpu.utils.hashing import mhash


def _ds(n=1000, seed=0):
    ds, _ = synthetic_classification(n, 500, density=0.02, seed=seed)
    return ds


def test_parquet_roundtrip(tmp_path):
    ds = _ds()
    paths = write_parquet_shards(ds, str(tmp_path / "shards"),
                                 rows_per_shard=300)
    assert len(paths) == 4
    back = read_parquet(str(tmp_path / "shards"))
    np.testing.assert_array_equal(ds.indices, back.indices)
    np.testing.assert_array_equal(ds.indptr, back.indptr)
    np.testing.assert_allclose(ds.values, back.values)
    np.testing.assert_allclose(ds.labels, back.labels)


def test_parquet_roundtrip_with_fields(tmp_path):
    n, L = 200, 5
    rng = np.random.default_rng(0)
    ds = SparseDataset(
        rng.integers(1, 100, n * L).astype(np.int32),
        np.arange(0, n * L + 1, L), np.ones(n * L, np.float32),
        rng.normal(0, 1, n).astype(np.float32),
        rng.integers(0, 8, n * L).astype(np.int32))
    write_parquet_shards(ds, str(tmp_path / "s"), rows_per_shard=64)
    back = read_parquet(str(tmp_path / "s"))
    np.testing.assert_array_equal(ds.fields, back.fields)


def test_string_features_table():
    import pyarrow as pa
    table = pa.table({
        "features": [["1:0.5", "7", "height:1.7"], ["2:2.0"]],
        "label": [1.0, -1.0],
    })
    ds = table_to_dataset(table, dims=1 << 16)
    assert len(ds) == 2
    i0, v0 = ds.row(0)
    assert list(i0[:2]) == [1, 7]
    assert i0[2] == mhash("height", (1 << 16) - 1)
    np.testing.assert_allclose(v0, [0.5, 1.0, 1.7])


def test_ffm_string_features_table():
    import pyarrow as pa
    table = pa.table({
        "features": [["2:11:0.5", "3:12"], ["0:1:1.0"]],
        "label": [1.0, -1.0],
    })
    ds = table_to_dataset(table, dims=1 << 16, ffm=True, num_fields=8)
    i0, v0 = ds.row(0)
    assert list(i0) == [11, 12]
    np.testing.assert_allclose(v0, [0.5, 1.0])
    assert list(ds.fields[:2]) == [2, 3]


def test_csv_reader(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("label,age,income\n1,30,5.5\n-1,40,0\n")
    ds = read_csv(str(p), dims=1 << 16)
    assert len(ds) == 2
    i0, v0 = ds.row(0)
    assert len(i0) == 2
    np.testing.assert_allclose(sorted(v0), [5.5, 30.0])
    i1, v1 = ds.row(1)       # zero income dropped (sparse semantics)
    assert len(i1) == 1 and v1[0] == 40.0


def test_stream_covers_every_row_once_per_epoch(tmp_path):
    ds = _ds(997)            # prime size: exercises the carry-over path
    write_parquet_shards(ds, str(tmp_path / "s"), rows_per_shard=250)
    stream = ParquetStream(str(tmp_path / "s"))
    assert len(stream) == 997
    seen = 0.0
    n_rows = 0
    for b in stream.batches(64, epochs=2, shuffle=True, seed=7):
        nv = b.n_valid or b.batch_size
        n_rows += nv
        seen += b.label[:nv].sum()
    assert n_rows == 2 * 997
    assert abs(seen - 2 * ds.labels.sum()) < 1e-3


def test_fit_stream_matches_in_ram_quality(tmp_path):
    from hivemall_tpu.models.linear import GeneralClassifier
    ds = _ds(2000, seed=3)
    write_parquet_shards(ds, str(tmp_path / "s"), rows_per_shard=512)
    opts = "-dims 1024 -loss logloss -opt adagrad -reg no -mini_batch 128"
    ram = GeneralClassifier(opts).fit(ds, epochs=2)
    stream = ParquetStream(str(tmp_path / "s"))
    oo = GeneralClassifier(opts).fit_stream(stream.batches(128, epochs=2))
    # same corpus, different order: equal quality, not equal bits
    assert abs(ram.cumulative_loss - oo.cumulative_loss) < 0.1
    from hivemall_tpu.frame.evaluation import auc
    assert auc(ds.labels, oo.predict_proba(ds)) > 0.9


def test_cli_trains_from_parquet_dir(tmp_path, capsys):
    from hivemall_tpu.cli.main import main
    ds = _ds(600, seed=5)
    write_parquet_shards(ds, str(tmp_path / "s"), rows_per_shard=200)
    model = str(tmp_path / "m.tsv")
    rc = main(["train", "--algo", "train_classifier",
               "--input", str(tmp_path / "s"),
               "--options", "-dims 1024 -mini_batch 64 -loss logloss "
                            "-opt adagrad -reg no",
               "--model", model])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"examples": 600' in out
    assert sum(1 for _ in open(model)) > 10


def test_frame_arrow_interchange(tmp_path):
    from hivemall_tpu.frame.dataframe import Frame
    f = Frame({"features": [["1:1.0", "2:0.5"], ["3:2.0"]],
               "label": [1.0, -1.0]})
    p = str(tmp_path / "f.parquet")
    f.to_parquet(p)
    back = Frame.from_parquet(p)
    assert len(back) == 2
    assert list(back["label"]) == [1.0, -1.0]
    assert list(back["features"][0]) == ["1:1.0", "2:0.5"]
    # trains straight off the round-tripped frame (HivemallOps-style)
    model = back.train_classifier("features", "label",
                                  "-dims 64 -mini_batch 2 -loss logloss "
                                  "-opt adagrad -reg no")
    assert len(model) > 0


def test_frame_from_csv(tmp_path):
    from hivemall_tpu.frame.dataframe import Frame
    p = tmp_path / "t.csv"
    p.write_text("a,b\n1,x\n2,y\n")
    f = Frame.from_csv(str(p))
    assert list(f["a"]) == [1, 2]
    assert list(f["b"]) == ["x", "y"]
