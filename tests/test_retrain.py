"""Autopilot retraining (docs/RELIABILITY.md "Autonomous retraining"):
the ReplayBuffer disk ring, the RouterTee/ShadowBuffer label-join tees,
warm-start fidelity of the composed retrain stream, the
RetrainController's storm controls (debounce, cooldown, backoff,
window budget, single-child budget, flap detector) and its
crash-recovery-from-disk contract, plus the votes-vs-acked SLO
surface. The full multi-process heal (drift votes → child retrain →
gate → canary → fleet convergence under live traffic) is pinned by the
retrain chaos smoke in run_tests.sh."""

import json
import os
import time

import numpy as np
import pytest

from hivemall_tpu.io import checkpoint as ck
from hivemall_tpu.serve.retrain import (ReplayBuffer, RetrainController,
                                        RouterTee, build_retrain_stream,
                                        retrain_stub)

OPTS = "-dims 512 -loss logloss -opt adagrad -mini_batch 16"


def _trainer(opts=OPTS):
    from hivemall_tpu.models.linear import GeneralClassifier
    return GeneralClassifier(opts)


def _raw_rows(ds, n, start=0):
    rows, labels = [], []
    for i in range(start, start + n):
        idx, val = ds.row(i % len(ds))
        rows.append([f"{int(a)}:{float(v)!r}" for a, v in zip(idx, val)])
        labels.append(float(ds.labels[i % len(ds)]))
    return rows, labels


@pytest.fixture()
def promoted_dir(tmp_path):
    """A checkpoint dir with a trained, PROMOTED bootstrap bundle."""
    from hivemall_tpu.io.libsvm import synthetic_classification
    ds, _ = synthetic_classification(128, 48, seed=3)
    t = _trainer()
    t.fit(ds)
    path = os.path.join(tmp_path, f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(path)
    ck.promote_bundle(str(tmp_path), path)
    return str(tmp_path), t, ds, path


# --- replay buffer -----------------------------------------------------------

def test_replay_ring_rotation_and_counters(tmp_path):
    rb = ReplayBuffer(str(tmp_path), segment_rows=4, max_segments=2)
    rows = [[f"{i + 1}:1.0"] for i in range(10)]
    labels = [1.0] * 10
    rb.add(rows, labels)
    rb.flush()
    c = rb.counters()
    assert c["rows"] == 10
    assert c["segments"] == 2                 # ring evicted the oldest
    assert c["rows_dropped"] == 4
    assert c["pending_rows"] == 0
    # committed content = the NEWEST rows (drop-oldest ring)
    back = rb.rows()
    assert len(back) == 6
    assert back[-1][0] == ["10:1.0"]
    # no tmp litter from the atomic writes
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


def test_replay_seq_recovers_across_instances(tmp_path):
    rb = ReplayBuffer(str(tmp_path), segment_rows=2, max_segments=10)
    rb.add([["1:1"], ["2:1"]], [1.0, -1.0])
    rb2 = ReplayBuffer(str(tmp_path), segment_rows=2, max_segments=10)
    rb2.add([["3:1"], ["4:1"]], [1.0, -1.0])
    segs = sorted(os.listdir(tmp_path))
    assert len(segs) == 2 and segs[0] != segs[1]
    assert len(rb2.rows()) == 4


def test_replay_skips_unlabeled_rows(tmp_path):
    rb = ReplayBuffer(str(tmp_path), segment_rows=8)
    n = rb.add([["1:1"], ["2:1"], ["3:1"]], [1.0, None, -1.0])
    assert n == 2
    rb.flush()
    assert [y for _, y in rb.rows()] == [1.0, -1.0]


def test_replay_dataset_roundtrip(tmp_path):
    from hivemall_tpu.io.libsvm import synthetic_classification
    ds, _ = synthetic_classification(32, 16, seed=5)
    rows, labels = _raw_rows(ds, 32)
    rb = ReplayBuffer(str(tmp_path), segment_rows=16)
    rb.add(rows, labels)
    rb.flush()
    t = _trainer()
    rds = rb.dataset(t)
    assert len(rds) == 32
    np.testing.assert_allclose(np.asarray(rds.labels),
                               np.asarray(labels, np.float32))
    # parsed through the trainer's own parser: same indices
    i0, v0 = t._parse_row(rows[0])
    np.testing.assert_array_equal(rds.row(0)[0], i0)


def test_router_tee_bounded_and_parsing():
    tee = RouterTee(capacity=3)
    for i in range(5):
        tee(json.dumps({"rows": [[f"{i + 1}:1.0"]]}).encode())
    assert tee.teed == 5 and tee.dropped == 2
    bodies = tee.drain()
    assert len(bodies) == 3 and tee.drain() == []
    assert RouterTee.rows_of(bodies[-1]) == [["5:1.0"]]
    assert RouterTee.rows_of(b'{"features": ["1:1", "2:2"]}') \
        == [["1:1", "2:2"]]
    assert RouterTee.rows_of(b"not json") == []


# --- shadow-buffer label-join tee -------------------------------------------

def test_shadow_raw_capture_and_drain_labeled():
    from hivemall_tpu.serve.promote import ShadowBuffer

    def label(row):
        if row[0].startswith("bad"):
            return None
        return 1.0 if row[0].startswith("1") else -1.0

    sh = ShadowBuffer(capacity=8, capture_raw=True, label_fn=label)
    sh.add([("p1",), ("p2",), ("p3",)],
           raw=[["1:1"], ["bad:1"], ["2:1"]])
    rows, labels = sh.drain_labeled()
    assert rows == [["1:1"], ["2:1"]] and labels == [1.0, -1.0]
    assert sh.drain_labeled() == ([], [])     # consumed
    assert sh.mirrored == 3
    # parsed-row mirror for the gate is unaffected by the raw drain
    assert len(sh.rows()) == 3


def test_batcher_raw_tee_alignment():
    from hivemall_tpu.serve.batcher import MicroBatcher
    got = []
    b = MicroBatcher(lambda rows: np.zeros(len(rows), np.float32),
                     max_batch=8, max_delay_ms=1.0)
    b.set_tee(lambda rows, raws: got.append((list(rows), list(raws))),
              raw=True)
    f1 = b.submit([("a",), ("b",)], raw=[["1:1"], ["2:1"]])
    f1.result(timeout=5)
    f2 = b.submit([("c",)])                   # no raw: None-padded
    f2.result(timeout=5)
    b.close()
    raws = [r for _, rs in got for r in rs]
    assert [["1:1"], ["2:1"]] == [r for r in raws if r is not None][:2]
    assert None in raws or len(raws) == 2     # the raw-less request pads
    rows_seen = [r for rows, _ in got for r in rows]
    assert rows_seen == [("a",), ("b",), ("c",)]


def test_shadow_counters_in_promotion_sections(tmp_path):
    from hivemall_tpu.serve.promote import (PromotionController,
                                            PromotionGate, ShadowBuffer,
                                            shadow_counters)
    sh = ShadowBuffer(capacity=4)
    sh.add([("r",)] * 6)
    gate = PromotionGate("train_classifier", "-dims 64", shadow=sh)
    ctrl = PromotionController(str(tmp_path), gate)
    sec = ctrl.obs_section()
    assert sec["shadow"] == {"mirrored": 6, "dropped": 2, "rows": 4}
    assert "retrain_acked" in sec
    assert shadow_counters(None) == {"mirrored": 0, "dropped": 0,
                                     "rows": 0}


# --- votes vs acked (obs/slo.py satellite) ----------------------------------

def test_slo_ack_retrain_counter():
    from hivemall_tpu.obs.slo import SloEngine
    eng = SloEngine()
    assert eng.retrain_acked == 0
    assert eng.ack_retrain(3) == 3
    assert eng.obs_section()["retrain_acked"] == 3
    assert eng.evaluate()["drift"]["retrain_acked"] == 3
    from hivemall_tpu.obs.report import render_slo
    assert "acked x3" in render_slo(eng.evaluate())


# --- warm-start fidelity (ISSUE 13 satellite) -------------------------------

@pytest.mark.parametrize("k", [1, 8])
def test_warm_start_fidelity_base_union_replay(tmp_path, k):
    """A retrain over build_retrain_stream (base file ∪ replay
    segments) warm-started from the promoted bundle must BIT-MATCH the
    same continuation run uninterrupted over the equivalent hand-built
    stream — the controller's data plumbing adds zero numerical drift,
    at steps_per_dispatch 1 and 8."""
    import itertools

    from hivemall_tpu.io.libsvm import (read_libsvm,
                                        synthetic_classification)
    opts = OPTS + f" -steps_per_dispatch {k}"
    base_ds, _ = synthetic_classification(96, 24, seed=7)
    # promoted bootstrap
    boot = _trainer(opts)
    boot.fit(base_ds)
    bpath = os.path.join(tmp_path, f"{boot.NAME}-step{boot._t:010d}.npz")
    boot.save_bundle(bpath)
    # base corpus as a file (the CLI/fleet train_input shape)
    base_path = str(tmp_path / "base.libsvm")
    with open(base_path, "w") as f:
        for i in range(len(base_ds)):
            idx, val = base_ds.row(i)
            toks = " ".join(f"{int(a)}:{float(v):.6f}"
                            for a, v in zip(idx, val))
            f.write(f"{int(base_ds.labels[i])} {toks}\n")
    # replay segments from 'live traffic'
    rdir = str(tmp_path / "replay")
    rb = ReplayBuffer(rdir, segment_rows=16)
    rows, labels = _raw_rows(base_ds, 40)
    rb.add(rows, labels)
    rb.flush()

    warm = _trainer(opts)
    warm.load_bundle(bpath)
    stream, n = build_retrain_stream(warm, base=base_path,
                                     replay_dir=rdir, batch_size=16)
    assert n == 96 + 40
    warm.fit_stream(stream)

    ref = _trainer(opts)
    ref.load_bundle(bpath)
    manual = itertools.chain(
        read_libsvm(base_path).batches(16, shuffle=False),
        ReplayBuffer(rdir).dataset(ref).batches(16, shuffle=False))
    ref.fit_stream(manual)

    assert warm._t == ref._t > boot._t
    np.testing.assert_array_equal(np.asarray(warm.w), np.asarray(ref.w))


# --- storm controls ----------------------------------------------------------

class _FakeChild:
    """Popen stand-in: exits immediately with a canned result line."""

    def __init__(self, rc=0):
        self._rc = rc

    def poll(self):
        return self._rc

    def terminate(self):
        pass

    kill = terminate

    def wait(self, timeout=None):
        return self._rc


def _fake_launch(result):
    """RetrainController._launch replacement producing ``result``."""
    def launch(self, warm_bundle):
        with self._lock:
            self._child = _FakeChild()
            self._child_out = [json.dumps(result)]
            self._child_since = time.monotonic()
    return launch


def _controller(ckdir, votes, **kw):
    kw.setdefault("cooldown_s", 30.0)
    kw.setdefault("min_votes", 2)
    kw.setdefault("flap_warmup", 10_000)
    return RetrainController("train_classifier", OPTS,
                             checkpoint_dir=ckdir,
                             votes_fn=lambda: votes[0], **kw)


def test_debounce_min_votes_and_trigger(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir
    votes = [0]
    c = _controller(ckdir, votes, train_input=None)
    # replay data so a trigger is possible
    rows, labels = _raw_rows(ds, 8)
    c.replay.add(rows, labels)
    monkeypatch.setattr(RetrainController, "_launch",
                        _fake_launch({"ok": True, "bundle": "x.npz",
                                      "step": 999}))
    c.tick()
    assert c.state == "idle" and c.attempts == 0
    votes[0] = 1
    c.tick()
    assert c.attempts == 0                    # below min_votes
    votes[0] = 2
    c.tick()
    assert c.attempts == 1                    # debounce satisfied
    assert c.votes_acked == 2
    assert c.state == "gating"                # fake child already done


def test_trigger_requires_promoted_and_data(tmp_path):
    votes = [10]
    c = _controller(str(tmp_path), votes)
    c.tick()
    assert c.attempts == 0
    assert "no PROMOTED bundle" in (c.last_error or "")


def test_cooldown_and_window_budget(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir
    votes = [0]
    c = _controller(ckdir, votes, cooldown_s=1000.0,
                    max_retrains_per_window=1, window_s=3600.0)
    rows, labels = _raw_rows(ds, 8)
    c.replay.add(rows, labels)
    monkeypatch.setattr(RetrainController, "_launch",
                        _fake_launch({"ok": True, "bundle": "x.npz",
                                      "step": 999}))
    votes[0] = 2
    c.tick()
    assert c.attempts == 1
    # resolve the candidate: reject it on disk -> backoff cooldown
    cand = c._candidate_path()
    open(cand, "wb").close()                  # file must exist
    ck.reject_bundle(cand, "test rejection")
    c.tick()
    assert c.state == "cooldown" and c.rejections == 1
    # more votes: cooldown holds (no second retrain inside the window)
    votes[0] = 10
    for _ in range(3):
        c.tick()
    assert c.attempts == 1
    # even past cooldown, the per-window budget would hold
    c._cooldown_until = 0.0
    c.tick()
    assert c.attempts == 1
    assert "budget exhausted" in (c.last_error or "")


def test_rejection_backoff_grows(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir
    votes = [0]
    c = _controller(ckdir, votes, cooldown_s=10.0, backoff_factor=3.0,
                    max_retrains_per_window=100)
    rows, labels = _raw_rows(ds, 8)
    c.replay.add(rows, labels)
    monkeypatch.setattr(RetrainController, "_launch",
                        _fake_launch({"ok": True, "bundle": "x.npz",
                                      "step": 999}))
    votes[0] = 2
    c.tick()
    cand = c._candidate_path()
    open(cand, "wb").close()
    ck.reject_bundle(cand, "r1")
    c.tick()
    rem1 = c.obs_section()["cooldown_remaining_s"]
    assert 25.0 < rem1 <= 30.0                # 10 * 3^1
    # second rejection backs off harder
    c._cooldown_until = 0.0
    c._set_state("idle", emit=False)
    votes[0] = 4
    c.tick()
    cand = c._candidate_path()
    open(cand, "wb").close()
    ck.reject_bundle(cand, "r2")
    c.tick()
    rem2 = c.obs_section()["cooldown_remaining_s"]
    assert 80.0 < rem2 <= 90.0                # 10 * 3^2


def test_single_child_budget(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir

    class _Running(_FakeChild):
        def poll(self):
            return None                       # never exits

    def launch(self, warm_bundle):
        with self._lock:
            self._child = _Running()
            self._child_out = []
            self._child_since = time.monotonic()

    votes = [2]
    c = _controller(ckdir, votes, train_timeout_s=10_000.0)
    rows, labels = _raw_rows(ds, 8)
    c.replay.add(rows, labels)
    monkeypatch.setattr(RetrainController, "_launch", launch)
    c.tick()
    assert c.attempts == 1 and c.state == "training"
    votes[0] = 50
    for _ in range(3):
        c.tick()
    assert c.attempts == 1                    # budget of exactly one


def test_child_timeout_fails_attempt(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir

    class _Stuck(_FakeChild):
        def poll(self):
            return None

    def launch(self, warm_bundle):
        with self._lock:
            self._child = _Stuck()
            self._child_out = []
            self._child_since = time.monotonic() - 999.0

    votes = [2]
    c = _controller(ckdir, votes, train_timeout_s=1.0)
    rows, labels = _raw_rows(ds, 8)
    c.replay.add(rows, labels)
    monkeypatch.setattr(RetrainController, "_launch", launch)
    c.tick()
    c.tick()
    assert c.state == "cooldown"
    assert "timed out" in (c.last_error or "")


def test_flap_detector_counts_and_holds(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir
    votes = [0]
    c = _controller(ckdir, votes, min_votes=1, flap_warmup=5,
                    cooldown_s=60.0)
    rows, labels = _raw_rows(ds, 8)
    c.replay.add(rows, labels)
    monkeypatch.setattr(RetrainController, "_launch",
                        _fake_launch({"ok": True, "bundle": "x.npz",
                                      "step": 999}))
    # calm-but-varying warmup (a constant stream has zero variance and
    # the self-calibrated threshold never arms; enough ticks that the
    # storm's own contribution to the Welford std is negligible — the
    # production regime, one observation per tick), then a vote storm:
    # the shared DriftWatch must flag and the holdoff must block the
    # trigger this tick despite pending >= min_votes
    for i in range(150):
        votes[0] += i % 2
        c._observe_votes(time.monotonic())
    c.votes_acked = c.votes_seen              # consume the warmup votes
    c._recent_votes.clear()
    votes[0] += 500
    c.tick()
    assert c.flaps >= 1
    assert c.attempts == 0                    # flap holdoff, not a storm
    assert c._flap_until > time.monotonic()


# --- crash recovery from on-disk state --------------------------------------

def test_recovery_honors_cooldown_stamp(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir
    votes = [5]
    a = _controller(ckdir, votes, cooldown_s=500.0)
    a._enter_cooldown(500.0)
    # fresh controller over the same dir (the crashed one is gone)
    b = _controller(ckdir, votes)
    assert b.state == "cooldown"
    assert b.obs_section()["cooldown_remaining_s"] > 400.0
    rows, labels = _raw_rows(ds, 8)
    b.replay.add(rows, labels)
    monkeypatch.setattr(RetrainController, "_launch",
                        _fake_launch({"ok": True, "bundle": "x.npz",
                                      "step": 999}))
    b.tick()
    assert b.attempts == 0                    # stamp holds post-crash


def test_recovery_training_without_candidate(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir
    votes = [2]
    a = _controller(ckdir, votes, cooldown_s=0.0)

    class _Running(_FakeChild):
        def poll(self):
            return None

    def launch(self, warm_bundle):
        with self._lock:
            self._child = _Running()
            self._child_out = []
            self._child_since = time.monotonic()

    rows, labels = _raw_rows(ds, 8)
    a.replay.add(rows, labels)
    monkeypatch.setattr(RetrainController, "_launch", launch)
    a.tick()
    assert a.state == "training"
    # SIGKILL: the child dies with the controller, no candidate landed
    b = _controller(ckdir, votes)
    assert b.state == "idle"
    assert "recovered" in (b.last_error or "")
    assert b.attempts == 1                    # durable counters survive


def test_recovery_gating_resumes_and_resolves(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir
    votes = [2]
    a = _controller(ckdir, votes, cooldown_s=1.0)
    rows, labels = _raw_rows(ds, 8)
    a.replay.add(rows, labels)
    # a REAL candidate bundle (promote_bundle reads its meta)
    t2 = _trainer()
    t2.load_bundle(path)
    t2._t += 7
    cand = os.path.join(ckdir, f"{t2.NAME}-step{t2._t:010d}.npz")
    t2.save_bundle(cand)
    monkeypatch.setattr(
        RetrainController, "_launch",
        _fake_launch({"ok": True, "bundle": os.path.basename(cand),
                      "step": int(t2._t)}))
    a.tick()
    assert a.state == "gating"
    # controller dies; a new one resumes watching the SAME candidate
    b = _controller(ckdir, votes)
    assert b.state == "gating"
    assert b._candidate["bundle"] == os.path.basename(cand)
    # external gate (fleet manager / promote watcher) canaries it...
    ck.promote_bundle(ckdir, cand, state="canary")
    b.tick()
    assert b.state == "canary"
    # ...another crash mid-canary: recovery lands back in canary
    c = _controller(ckdir, votes)
    assert c.state == "canary"
    # bake completes -> promoted -> success + cooldown
    ck.finalize_promotion(ckdir)
    c.tick()
    assert c.state == "cooldown" and c.successes == 1


def test_recovery_canary_rollback_counts(promoted_dir, monkeypatch):
    ckdir, t, ds, path = promoted_dir
    votes = [2]
    a = _controller(ckdir, votes, cooldown_s=1.0)
    rows, labels = _raw_rows(ds, 8)
    a.replay.add(rows, labels)
    t2 = _trainer()
    t2.load_bundle(path)
    t2._t += 7
    cand = os.path.join(ckdir, f"{t2.NAME}-step{t2._t:010d}.npz")
    t2.save_bundle(cand)
    monkeypatch.setattr(
        RetrainController, "_launch",
        _fake_launch({"ok": True, "bundle": os.path.basename(cand),
                      "step": int(t2._t)}))
    a.tick()
    ck.promote_bundle(ckdir, cand, state="canary")
    a.tick()
    assert a.state == "canary"
    # the bake fails: manager quarantines + rolls back (marker FIRST)
    ck.reject_bundle(cand, "canary regression")
    ck.rollback_promoted(ckdir, "canary regression")
    a.tick()
    assert a.state == "cooldown"
    assert a.rollbacks == 1 and a.rejections == 1


def test_vote_counter_reset_rebaselines(promoted_dir):
    ckdir, t, ds, path = promoted_dir
    votes = [50]
    c = _controller(ckdir, votes)
    c.tick()                                  # baseline at 50, no lump
    assert c.attempts == 0 and c.votes_seen == 50
    votes[0] = 3                              # serve process restarted
    c.tick()
    assert c.votes_seen == 3
    assert c.votes_acked <= 3                 # ledger clamped, no
    #                                           phantom pending votes


# --- obs / stub / events -----------------------------------------------------

def test_retrain_obs_section_and_stub(promoted_dir):
    ckdir, t, ds, path = promoted_dir
    votes = [0]
    c = _controller(ckdir, votes)
    sec = c.obs_section()
    assert set(sec) == set(retrain_stub())
    assert set(sec["replay"]) == set(retrain_stub()["replay"])
    assert sec["configured"] is True and sec["state"] == "idle"
    # registry provider is live (weakly held)
    from hivemall_tpu.obs.registry import registry
    assert registry.snapshot()["retrain"]["configured"] is True


def test_retrain_events_emitted(promoted_dir, monkeypatch, tmp_path):
    from hivemall_tpu.utils import metrics as m
    ckdir, t, ds, path = promoted_dir
    stream_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("HIVEMALL_TPU_METRICS", stream_path)
    m._stream = None                          # force re-open on new env
    try:
        votes = [2]
        c = _controller(ckdir, votes, cooldown_s=1.0)
        rows, labels = _raw_rows(ds, 8)
        c.replay.add(rows, labels)
        monkeypatch.setattr(RetrainController, "_launch",
                            _fake_launch({"ok": True, "bundle": "x.npz",
                                          "step": 999}))
        c.tick()
        cand = c._candidate_path()
        open(cand, "wb").close()
        ck.reject_bundle(cand, "bad data")
        c.tick()
        with open(stream_path) as f:
            events = [json.loads(ln) for ln in f if ln.strip()]
        kinds = [e["event"] for e in events]
        assert "retrain" in kinds
        rej = [e for e in events if e["event"] == "retrain"
               and e.get("outcome") == "rejected"]
        assert rej and "bad data" in rej[0]["reason"]
    finally:
        m._stream = None


def test_label_shift_source_join_and_poison():
    from hivemall_tpu.testing.faults import LabelShiftSource
    src = LabelShiftSource(seed=4)
    rows, labels = src.rows(32)
    # the label join recovers exactly the generated ground truth
    assert [src.label(r) for r in rows] == labels
    assert 0.5 < np.mean(np.asarray(labels) > 0) < 1.0   # biased concept
    src.shift()
    rows2, labels2 = src.rows(8)
    # disjoint index ranges per phase
    ids1 = {int(f.split(":")[0]) for r in rows for f in r}
    ids2 = {int(f.split(":")[0]) for r in rows2 for f in r}
    assert not (ids1 & ids2)
    # late-joined phase-0 rows still label correctly after the shift
    assert [src.label(r) for r in rows] == labels
    src.poison()
    assert [src.label(r) for r in rows2] == [-y for y in labels2]
    assert src.label(["garbage"]) is None


def test_cli_retrain_status(promoted_dir, capsys):
    from hivemall_tpu.cli.main import main
    ckdir, t, ds, path = promoted_dir
    rc = main(["retrain", "--algo", "train_classifier",
               "--options", OPTS, "--checkpoint-dir", ckdir,
               "--status"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["section"]["configured"] is True
    assert out["promoted"]["current"]["step"] == ck.read_promoted(
        ckdir)["current"]["step"]
