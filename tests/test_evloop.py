"""Event-driven serving plane (hivemall_tpu/serve/evloop.py,
docs/SERVING.md "Serving planes"): the HMF1 binary wire codec, the
inline batch assembler's BatchPlane contracts, and the evloop server's
protocol surface — frame/JSON bit-match, malformed-frame teardown that
leaves the loop healthy, hop-header additivity on BOTH planes and the
UDS transport."""

import os

import numpy as np
import pytest

from hivemall_tpu.serve.wire import (CONTENT_TYPE_FRAME, MAGIC, WireError,
                                     decode_frame, encode_frame)

OPTS = "-dims 1024 -loss logloss -opt adagrad -mini_batch 32"


# --- wire codec (no server, no jax) -----------------------------------------

def _rows(n, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(rng.integers(1, 9))
        out.append((rng.integers(0, 1 << 20, k).astype(np.int32),
                    rng.random(k).astype(np.float32)))
    return out


def test_wire_frame_roundtrip():
    rows = _rows(5)
    dec, dl = decode_frame(encode_frame(rows))
    assert dl is None and len(dec) == len(rows)
    for (ai, av), (bi, bv) in zip(rows, dec):
        assert np.array_equal(ai, bi)
        assert np.array_equal(av, bv)          # f32 bits survive the wire
        assert bi.dtype == np.int32 and bv.dtype == np.float32
    # deadline flag carries a per-request budget
    _, dl = decode_frame(encode_frame(rows[:1], deadline_ms=7.5))
    assert dl == pytest.approx(7.5)
    # degenerate shapes: empty frame, zero-feature row
    assert decode_frame(encode_frame([])) == ([], None)
    dec, _ = decode_frame(encode_frame(
        [(np.zeros(0, np.int32), np.zeros(0, np.float32))]))
    assert len(dec) == 1 and len(dec[0][0]) == 0


def test_wire_rejects_malformed_frames():
    good = encode_frame(_rows(2))
    cases = [
        b"",                                   # shorter than the header
        b"NOPE" + good[4:],                    # bad magic
        bytes([good[0], good[1], good[2], good[3], 0xFE]) + good[5:],
        good[:-3],                             # truncated in row payload
        good[:7],                              # truncated at row length
        good + b"\x00",                        # trailing garbage
        encode_frame(_rows(1), deadline_ms=1.0)[:9],  # cut in deadline
    ]
    for bad in cases:
        with pytest.raises(WireError):
            decode_frame(bad)
    # per-row feature cap (the engine's bound) fails BEFORE allocation
    wide = encode_frame([(np.arange(3, dtype=np.int32),
                          np.ones(3, np.float32))])
    with pytest.raises(WireError, match="cap"):
        decode_frame(wide, max_row_features=1)
    # encode-side validation: mismatched idx/val shapes never hit the wire
    with pytest.raises(WireError, match="mismatch"):
        encode_frame([(np.zeros(3, np.int32), np.zeros(2, np.float32))])
    assert good[:4] == MAGIC


# --- inline assembler: BatchPlane contracts (pure, loop-free) ----------------

def _mk_done(sink):
    def done(scores, meta, hop, exc):
        sink.append((scores, meta, hop, exc))
    return done


def test_inline_assembler_contracts():
    from hivemall_tpu.serve.batcher import ServeDeadline, ServeOverload
    from hivemall_tpu.serve.evloop import InlineAssembler
    calls = []

    def predict(rows):
        calls.append(len(rows))
        return np.arange(len(rows), dtype=np.float32)

    a = InlineAssembler(predict, max_batch=4, max_delay_ms=0.0,
                        max_queue_rows=6)
    got = []
    # never-split: 3 + 2 rows > max_batch 4 -> two predict calls, each
    # request's slice intact
    a.submit([1, 2, 3], _mk_done(got))
    a.submit([4, 5], _mk_done(got))
    a.pump()
    assert calls == [3, 2]
    assert np.array_equal(got[0][0], [0.0, 1.0, 2.0])
    assert np.array_equal(got[1][0], [0.0, 1.0])
    # hop decomposition present on every completion
    assert {"queue_s", "assemble_s", "predict_s"} <= set(got[0][2])
    # shed rule: a full queue rejects synchronously...
    a.submit([1] * 5, _mk_done(got))
    with pytest.raises(ServeOverload):
        a.submit([1, 2], _mk_done(got))
    assert a.shed == 1
    a.pump()
    # ...but one oversized request against an EMPTY queue is admitted
    a.submit([1] * 9, _mk_done(got))
    a.pump()
    assert calls[-1] == 9
    # deadline is judged at pop: a lapsed budget completes with
    # ServeDeadline and never reaches the predict fn
    n_calls = len(calls)
    a.submit([1], _mk_done(got), deadline_ms=0.001)
    import time
    time.sleep(0.005)
    a.pump()
    assert len(calls) == n_calls and a.expired == 1
    assert isinstance(got[-1][3], ServeDeadline)
    # drain close scores everything pending; submit-after-close raises
    a.submit([7], _mk_done(got))
    a.close(drain=True)
    assert got[-1][3] is None and np.array_equal(got[-1][0], [0.0])
    with pytest.raises(RuntimeError):
        a.submit([8], _mk_done(got))


# --- evloop server protocol surface ------------------------------------------

@pytest.fixture()
def trained(tmp_path):
    from hivemall_tpu.io.libsvm import synthetic_classification
    from hivemall_tpu.models.linear import GeneralClassifier
    ds, _ = synthetic_classification(120, 64, seed=11)
    t = GeneralClassifier(OPTS)
    t.fit(ds)
    path = os.path.join(tmp_path, f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(path)
    return t, ds, str(tmp_path), path


def _engine(ckdir, **kw):
    from hivemall_tpu.serve.engine import PredictEngine
    kw.setdefault("warmup", False)
    kw.setdefault("max_batch", 8)      # few compile buckets: tier-1 budget
    return PredictEngine("train_classifier", OPTS, checkpoint_dir=ckdir,
                         **kw)


def _feat_rows(ds, n):
    out = []
    for i in range(n):
        idx, val = ds.row(i)
        out.append([f"{int(a)}:{float(v)!r}" for a, v in zip(idx, val)])
    return out


def _ref(t, rows):
    from hivemall_tpu.io.sparse import SparseDataset
    parsed = [t._parse_row(r) for r in rows]
    return t.predict_proba(SparseDataset.from_rows(parsed,
                                                   [1.0] * len(parsed)))


def _evsrv(eng, **kw):
    from hivemall_tpu.serve.evloop import EvloopPredictServer
    kw.setdefault("max_delay_ms", 1.0)
    return EvloopPredictServer(eng, port=0, watch=False, slo=False,
                               **kw).start()


def test_evloop_frame_bitmatches_json_and_mixed_clients(trained):
    """Binary frames and JSON strings negotiate per-request on ONE
    listener and score to identical bits — a frame client and a string
    client share a replica without either noticing the other."""
    from hivemall_tpu.serve.client import RawHTTPClient
    t, ds, ckdir, _ = trained
    rows = _feat_rows(ds, 6)
    ref = _ref(t, rows)
    srv = _evsrv(_engine(ckdir))
    cli_s = cli_b = None
    try:
        cli_s = RawHTTPClient("127.0.0.1", srv.port)
        cli_b = RawHTTPClient("127.0.0.1", srv.port)
        code, rs = cli_s.post_json("/predict", {"rows": rows})
        assert code == 200
        parsed = [t._parse_row(r) for r in rows]
        code, rb = cli_b.post_frame("/predict", parsed)
        assert code == 200
        js = np.asarray(rs["scores"], np.float32)
        fb = np.asarray(rb["scores"], np.float32)
        assert np.array_equal(js, ref)
        assert np.array_equal(fb, ref)          # bit-match across formats
        assert rb["model_step"] == rs["model_step"]
        # interleave the two protocols on their kept-alive connections
        for i in range(3):
            _, r1 = cli_b.post_frame("/predict", [parsed[i]])
            _, r2 = cli_s.post_json("/predict", {"rows": [rows[i]]})
            assert np.float32(r1["scores"][0]) == ref[i]
            assert np.float32(r2["scores"][0]) == ref[i]
    finally:
        for c in (cli_s, cli_b):
            if c is not None:
                c.close()
        srv.stop()


def test_evloop_malformed_frame_400_closes_without_poisoning_loop(trained):
    """A desynced binary stream answers 400 AND closes (no resync is
    possible mid-connection) — and the event loop keeps serving other
    connections untouched."""
    from hivemall_tpu.serve.client import (RawConn, RawHTTPClient,
                                           build_request, read_response)
    t, ds, ckdir, _ = trained
    rows = _feat_rows(ds, 2)
    ref = _ref(t, rows)
    srv = _evsrv(_engine(ckdir))
    cli = None
    try:
        conn = RawConn("127.0.0.1", srv.port, timeout=10.0)
        try:
            req = build_request("127.0.0.1", srv.port, "/predict",
                                b"JUNKJUNKJUNK", ctype=CONTENT_TYPE_FRAME)
            conn.sock.sendall(req)
            status, lines, payload = read_response(conn.rfile)
            assert status == 400
            assert b"error" in payload
            assert any(h.lower().startswith(b"connection: close")
                       for h in lines)
            # the server actually hangs up: EOF, not a stalled read
            conn.sock.settimeout(5.0)
            assert conn.rfile.read(1) == b""
        finally:
            conn.close()
        # a truncated frame (valid magic, lying row count) also tears down
        conn = RawConn("127.0.0.1", srv.port, timeout=10.0)
        try:
            parsed = [t._parse_row(r) for r in rows]
            cut = encode_frame(parsed)[:-3]
            conn.sock.sendall(build_request(
                "127.0.0.1", srv.port, "/predict", cut,
                ctype=CONTENT_TYPE_FRAME))
            status, lines, _ = read_response(conn.rfile)
            assert status == 400
            assert any(h.lower().startswith(b"connection: close")
                       for h in lines)
        finally:
            conn.close()
        # the loop is not poisoned: fresh clients, both protocols, still
        # score to the exact reference (a malformed JSON 400 keeps alive)
        cli = RawHTTPClient("127.0.0.1", srv.port)
        code, _ = cli.request("POST", "/predict", b"{nope")
        assert code == 400
        code, r = cli.post_json("/predict", {"rows": rows})  # same conn
        assert code == 200
        assert np.array_equal(np.asarray(r["scores"], np.float32), ref)
        code, r = cli.post_frame("/predict",
                                 [t._parse_row(x) for x in rows])
        assert code == 200
        assert np.array_equal(np.asarray(r["scores"], np.float32), ref)
    finally:
        if cli is not None:
            cli.close()
        srv.stop()


def test_hop_header_parts_sum_on_both_planes(trained):
    """Every /predict response decomposes its wall time into hop parts
    that sum to total on BOTH planes; the evloop plane adds a leading
    ``loop`` component (event-loop dwell) the threaded plane lacks."""
    from hivemall_tpu.serve.client import RawHTTPClient
    from hivemall_tpu.serve.http import PredictServer
    t, ds, ckdir, _ = trained
    rows = _feat_rows(ds, 2)
    threaded_keys = {"parse", "queue", "assemble", "predict", "other",
                     "total"}
    for plane in ("threaded", "evloop"):
        eng = _engine(ckdir)
        if plane == "evloop":
            srv = _evsrv(eng)
        else:
            srv = PredictServer(eng, port=0, max_delay_ms=1.0,
                                watch=False, slo=False).start()
        cli = RawHTTPClient("127.0.0.1", srv.port)
        try:
            code, _ = cli.post_json("/predict", {"rows": rows})
            assert code == 200
            hdrs = {k.lower(): v for k, v in cli.last_headers.items()}
            hop = dict(kv.split("=")
                       for kv in hdrs["x-hivemall-hop"].split(","))
            want = (threaded_keys | {"loop"} if plane == "evloop"
                    else threaded_keys)
            assert set(hop) == want, plane
            total = float(hop.pop("total"))
            parts = sum(float(v) for v in hop.values())
            # "other" absorbs the residual -> the decomposition is
            # additive up to the 3-decimal header rounding
            assert parts == pytest.approx(total, abs=0.02), plane
            assert float(hop["predict"]) > 0, plane
        finally:
            cli.close()
            srv.stop()


def test_evloop_uds_transport_bitmatches_tcp(trained, tmp_path):
    """One evloop server listens on TCP and a unix socket at once; the
    UDS fast path returns byte-identical scores and survives keep-alive
    reuse (the router's co-located transport)."""
    from hivemall_tpu.serve.client import RawHTTPClient
    t, ds, ckdir, _ = trained
    rows = _feat_rows(ds, 3)
    ref = _ref(t, rows)
    uds = os.path.join(str(tmp_path), "replica.sock")
    srv = _evsrv(_engine(ckdir), uds_path=uds)
    tcp = via_uds = None
    try:
        assert srv.uds_path == uds and os.path.exists(uds)
        tcp = RawHTTPClient("127.0.0.1", srv.port)
        via_uds = RawHTTPClient("127.0.0.1", srv.port, uds=uds)
        code, ru = via_uds.post_json("/predict", {"rows": rows})
        assert code == 200
        code, rt = tcp.post_json("/predict", {"rows": rows})
        assert code == 200
        assert np.array_equal(np.asarray(ru["scores"], np.float32), ref)
        assert np.array_equal(np.asarray(rt["scores"], np.float32), ref)
        # keep-alive reuse over the unix socket, frames included
        for i in range(2):
            _, r = via_uds.post_frame("/predict", [t._parse_row(rows[i])])
            assert np.float32(r["scores"][0]) == ref[i]
        # /healthz answers on the UDS listener too
        code, hz = via_uds.post_json("/healthz", {})
        assert code == 200 and hz["status"] == "ok"
    finally:
        for c in (tcp, via_uds):
            if c is not None:
                c.close()
        srv.stop()
    assert not os.path.exists(uds)     # teardown unlinks the socket file
