"""Round-3 mesh coverage (SURVEY §3.17): ensemble-parallel trees over dp
and covariance (CW/AROW) replicas with argmin-KLD mixing, on the
8-virtual-device CPU mesh."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hivemall_tpu.parallel.mesh import make_mesh


def test_rf_mesh_matches_single_device():
    from hivemall_tpu.models.trees import RandomForestClassifier
    rng = np.random.default_rng(0)
    n, d = 400, 6
    X = rng.normal(0, 1, (n, d)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int32)
    a = RandomForestClassifier("-trees 8 -depth 4 -seed 5")
    a.fit(X, y)
    b = RandomForestClassifier("-trees 8 -depth 4 -seed 5 -mesh dp=4")
    b.fit(X, y)
    # same seeds, same bootstrap -> identical forests
    np.testing.assert_array_equal(a.tree.feat, b.tree.feat)
    np.testing.assert_array_equal(a.tree.thr, b.tree.thr)
    np.testing.assert_allclose(a.tree.value, b.tree.value,
                               rtol=1e-5, atol=1e-5)
    acc = (b.predict(X) == y).mean()
    assert acc > 0.9, acc


def test_rf_mesh_validates():
    from hivemall_tpu.models.trees import RandomForestClassifier
    with pytest.raises(ValueError, match="divide"):
        RandomForestClassifier("-trees 6 -depth 3 -mesh dp=4").fit(
            np.zeros((64, 4), np.float32), np.zeros(64, np.int32))


def test_covariance_replicas_argmin_kld():
    from hivemall_tpu.models.classifier import AROWTrainer
    from hivemall_tpu.parallel.mix import make_covariance_replica_step
    dp = 4
    mesh = make_mesh(dp=dp)
    rates = AROWTrainer("-dims 128")._rates()
    step = make_covariance_replica_step(mesh, rates, mix_every=2)
    N = 128
    w = jnp.zeros((dp, N))
    sig = jnp.ones((dp, N))
    rng = np.random.default_rng(1)
    B = dp * 16
    planted = rng.normal(0, 1, N).astype(np.float32)
    losses = []
    for t in range(6):
        idx = rng.integers(1, N, (B, 4)).astype(np.int32)
        val = rng.uniform(0.5, 1.5, (B, 4)).astype(np.float32)
        lab = np.sign(planted[idx].sum(1) + 1e-3).astype(np.float32)
        w, sig, ls = step(w, sig, float(t), jnp.asarray(idx),
                          jnp.asarray(val), jnp.asarray(lab))
        losses.append(float(ls))
    # after a mix step (t=1, 3, 5) all replicas hold the same state
    np.testing.assert_allclose(np.asarray(w[0]), np.asarray(w[-1]),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sig[0]), np.asarray(sig[-1]),
                               rtol=1e-6)
    assert losses[-1] < losses[0], losses
    assert (np.asarray(sig) <= 1.0 + 1e-6).all()   # variances shrink
