"""Multi-process (DCN-path) smoke: jax.distributed bootstrap via
parallel.mesh.init_distributed + a cross-process pmean collective.

The reference's NCCL/MPI analog (SURVEY.md §6 'distributed communication
backend'): two REAL processes form a cluster over the coordination service
(gloo on CPU), build a global 2-device mesh (one device per process) and
run a shard_map pmean — the same substrate a multi-host TPU fleet uses
over DCN. Mirrors the reference's in-process-localhost-MixServer trick at
the collectives layer (SURVEY.md §5.3).
"""

import socket
import subprocess
import sys
import textwrap

WORKER = textwrap.dedent("""
    import os, sys
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)       # one device per process
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from hivemall_tpu.parallel.mesh import init_distributed
    port, rank = sys.argv[1], int(sys.argv[2])
    init_distributed(coordinator_address="127.0.0.1:" + port,
                     num_processes=2, process_id=rank)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    devs = jax.devices()
    assert len(devs) == 2, devs             # global device view
    assert jax.process_count() == 2
    mesh = Mesh(devs, ("dp",))
    f = jax.jit(shard_map(lambda a: jax.lax.pmean(a, "dp"), mesh=mesh,
                          in_specs=P("dp"), out_specs=P("dp")))
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")),
        np.ones(4, np.float32) * (rank + 1), (8,))
    out = f(garr)
    local = np.asarray(out.addressable_shards[0].data)
    assert np.allclose(local, 1.5), local   # mean of ranks 1 and 2
    print("rank", rank, "ok", flush=True)
""")


def test_two_process_dcn_pmean(tmp_path):
    import os
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    script = tmp_path / "worker.py"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script.write_text(WORKER % {"repo": repo})
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(port), str(r)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:          # never orphan a hung rank
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]
        assert "ok" in out
