"""Multi-process (DCN-path) smoke: jax.distributed bootstrap via
parallel.mesh.init_distributed + a cross-process pmean collective.

The reference's NCCL/MPI analog (SURVEY.md §6 'distributed communication
backend'): two REAL processes form a cluster over the coordination service
(gloo on CPU), build a global 2-device mesh (one device per process) and
run a shard_map pmean — the same substrate a multi-host TPU fleet uses
over DCN. Mirrors the reference's in-process-localhost-MixServer trick at
the collectives layer (SURVEY.md §5.3).
"""

import socket
import subprocess
import sys
import textwrap

WORKER = textwrap.dedent("""
    import os, sys
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)       # one device per process
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from hivemall_tpu.parallel.mesh import init_distributed
    port, rank = sys.argv[1], int(sys.argv[2])
    init_distributed(coordinator_address="127.0.0.1:" + port,
                     num_processes=2, process_id=rank)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    devs = jax.devices()
    assert len(devs) == 2, devs             # global device view
    assert jax.process_count() == 2
    mesh = Mesh(devs, ("dp",))
    f = jax.jit(shard_map(lambda a: jax.lax.pmean(a, "dp"), mesh=mesh,
                          in_specs=P("dp"), out_specs=P("dp")))
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")),
        np.ones(4, np.float32) * (rank + 1), (8,))
    out = f(garr)
    local = np.asarray(out.addressable_shards[0].data)
    assert np.allclose(local, 1.5), local   # mean of ranks 1 and 2
    print("rank", rank, "ok", flush=True)
""")


# the capability this test needs: cross-process collectives on the local
# backend. jaxlib's CPU backend (through at least 0.4/0.5) rejects them
# with exactly this error — a build/environment limitation, not a repo
# regression, so it must skip, not fail (GPU/TPU runs still assert).
_NO_MP_COLLECTIVES = "Multiprocess computations aren't implemented"


def test_two_process_dcn_pmean(tmp_path):
    import os

    import pytest
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    script = tmp_path / "worker.py"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script.write_text(WORKER % {"repo": repo})
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(port), str(r)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:          # never orphan a hung rank
            if p.poll() is None:
                p.kill()
    if any(rc != 0 and _NO_MP_COLLECTIVES in err for rc, _, err in outs):
        pytest.skip("backend lacks multiprocess collectives "
                    "(CPU-only jaxlib); DCN pmean needs a real "
                    "distributed backend")
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]
        assert "ok" in out


def test_mix_server_stats_and_throttle():
    """EVENT_STATS counters probe (the JMX-metrics analog) and the
    key-updates/s throttle (reference MixServer throttling)."""
    import socket
    import struct
    import time as _time
    import json
    import numpy as np
    from hivemall_tpu.parallel.mix_service import (MixServer, MixMessage,
                                                   EVENT_AVERAGE,
                                                   EVENT_STATS)

    srv = MixServer().start()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        f = s.makefile("rwb")

        def send(msg):
            f.write(msg.encode())
            f.flush()
            ln = struct.unpack("<I", f.read(4))[0]
            return MixMessage.decode(f.read(ln))

        keys = np.arange(100, dtype=np.int64)
        send(MixMessage(EVENT_AVERAGE, "g", keys,
                        np.ones(100, np.float32), np.ones(100, np.float32),
                        np.ones(100, np.int32)))
        z = np.zeros(0)
        rep = send(MixMessage(EVENT_STATS, "", z.astype(np.int64),
                              z.astype(np.float32), z.astype(np.float32),
                              z.astype(np.int32)))
        stats = json.loads(rep.group)
        assert stats["requests"] == 1 and stats["keys_folded"] == 100
        assert stats["keys_tracked"] == 100 and stats["groups"] == 1

        # throttle: 1000 keys/s cap makes a 500-key burst take >= ~0.3s
        srv.throttle_keys_per_s = 1000
        t0 = _time.monotonic()
        for _ in range(4):
            send(MixMessage(EVENT_AVERAGE, "g", keys,
                            np.ones(100, np.float32),
                            np.ones(100, np.float32),
                            np.ones(100, np.int32)))
        assert _time.monotonic() - t0 > 0.25
        s.close()
    finally:
        srv.stop()


def test_np_index_vectorized_growth_and_duplicates():
    import numpy as np
    from hivemall_tpu.parallel.mix_service import _NpIndex
    ix = _NpIndex(cap_bits=3)
    rng = np.random.default_rng(3)
    seen = {}
    for _ in range(30):
        ks = rng.integers(-500, 500, rng.integers(1, 200))
        rows = ix.lookup_or_insert(ks)
        assert (rows == ix.lookup_or_insert(ks)).all()   # stable
        for k, r in zip(ks.tolist(), rows.tolist()):
            assert seen.setdefault(k, r) == r
    assert ix.n == len(seen)
