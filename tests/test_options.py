import pytest

from hivemall_tpu.utils.options import (HelpRequested, OptionError, OptionSpec)


def spec():
    return (OptionSpec("train_classifier")
            .add("loss", "loss_function", default="hingeloss",
                 help="loss function")
            .add("opt", "optimizer", default="sgd")
            .add("eta0", type=float, default=0.1)
            .add("iters", "iterations", type=int, default=1)
            .flag("dense", "densemodel", help="use dense model"))


def test_defaults():
    ns = spec().parse(None)
    assert ns.loss == "hingeloss" and ns.eta0 == 0.1 and ns.dense is False


def test_parse_mixed():
    ns = spec().parse("-loss logloss -opt AdaGrad -eta0 0.5 -dense -iters 10")
    assert ns.loss == "logloss"
    assert ns.opt == "AdaGrad"
    assert ns.eta0 == 0.5
    assert ns.dense is True
    assert ns.iters == 10 and ns.iterations == 10  # long alias mirrors


def test_long_names():
    ns = spec().parse("--iterations 3 --densemodel")
    assert ns.iters == 3 and ns.dense is True


def test_unknown_raises():
    with pytest.raises(OptionError):
        spec().parse("-nope 1")


def test_missing_arg_raises():
    with pytest.raises(OptionError):
        spec().parse("-eta0")


def test_help():
    with pytest.raises(HelpRequested) as e:
        spec().parse("-help")
    assert "train_classifier" in e.value.usage
    assert "-loss" in e.value.usage


def test_quoted_values():
    ns = OptionSpec("f").add("mix").parse("-mix 'host1,host2'")
    assert ns.mix == "host1,host2"
