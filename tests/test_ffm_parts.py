"""-ffm_table parts: the Pallas VMEM scatter+AdaGrad FFM layout.

Covers (reference: FieldAwareFactorizationMachineUDTF semantics,
SURVEY.md §3.6): step equivalence vs an XLA scatter oracle, trainer-level
fit/score/emission, kernel-grid padding of partial batches, and the
unsupported-combination guards. Runs on the CPU mesh via the kernel's
interpret mode.
"""
import os
import tempfile

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hivemall_tpu.io.sparse import SparseBatch, SparseDataset
from hivemall_tpu.models.fm import FFMTrainer
from hivemall_tpu.ops import fm_pallas as fp
from hivemall_tpu.ops.losses import get_loss

B, F, K, MRF = 128, 31, 8, 1 << 10   # Wp = 31*8+8 -> 256 (HP=2)
L = F
DIMS = 1 << 16


def _mk_batch(rng, b=B, zero_frac=0.1):
    idx = rng.integers(0, 1 << 20, (b, L)).astype(np.int32)
    idx[rng.random((b, L)) < zero_frac] = 0
    val = (idx != 0).astype(np.float32)
    lab = (rng.integers(0, 2, b) * 2 - 1).astype(np.float32)
    return idx, val, lab


def _oracle_step(params, opt_state, t, idx, val, label, row_mask, eta=0.1):
    """XLA scatter + dense AdaGrad with the identical math."""
    loss = get_loss("logloss")
    wp, hp = 256, 2
    T2, w0 = params["T2"], params["w0"]
    S2 = opt_state["T2"]["gg"]
    b = idx.shape[0]
    idxT, valT = idx.T, val.T
    fieldT = (jnp.arange(L, dtype=jnp.int32) % F)[:, None]
    rows = fp.parts_row_hash(idxT, fieldT, MRF)
    slab = T2.reshape(F * MRF, hp, 128)[rows]

    def batch_loss(w0f, slabf):
        phi = fp._phi_parts(w0f, slabf.reshape(L, b, wp), valT, F, K)
        return (loss.loss(phi, label) * row_mask).sum()

    loss_sum, (g0, gslab) = jax.value_and_grad(
        batch_loss, argnums=(0, 1))(w0.astype(jnp.float32), slab)
    gslab = gslab.astype(jnp.bfloat16).astype(jnp.float32)
    G = jnp.zeros((F * MRF, hp, 128), jnp.float32).at[rows].add(
        gslab.reshape(L, b, hp, 128))
    G2 = G.reshape(F * MRF * hp, 128)
    gg = S2 + G2 * G2
    T2n = (T2.astype(jnp.float32)
           - eta * G2 / (jnp.sqrt(gg) + 1e-6)).astype(T2.dtype)
    return T2n, gg, loss_sum


def test_geometry():
    mrf, wp, hp = fp.parts_geometry(1 << 24, 40, 4)
    assert (mrf, wp, hp) == (8192, 256, 2)
    assert 40 * mrf >= (1 << 24) // 64          # joint-capacity parity
    mrf2, wp2, hp2 = fp.parts_geometry(1 << 16, 31, 8)
    assert wp2 == 256 and hp2 == 2


def test_step_matches_oracle():
    rng = np.random.default_rng(1)
    idx, val, lab = _mk_batch(rng)
    mask = np.ones(B, np.float32)
    mask[-5:] = 0.0
    loss = get_loss("logloss")
    interp = jax.default_backend() != "tpu"
    step = fp.make_parts_step(loss, lambda t: 0.1, (0.0, 0.0, 0.0),
                              F, K, MRF, interpret=interp)

    key = jax.random.PRNGKey(0)
    Tl = jnp.concatenate([
        jax.random.normal(key, (F * MRF, F * K)) * 0.1,
        jnp.zeros((F * MRF, 256 - F * K))], axis=1)
    T2_np = np.asarray(Tl.reshape(F * MRF * 2, 128).astype(jnp.bfloat16))
    params = {"T2": jnp.asarray(T2_np), "w0": jnp.zeros((), jnp.float32)}
    opt = {"T2": {"gg": jnp.zeros((F * MRF * 2, 128), jnp.float32)},
           "w0": {"gg": jnp.zeros((), jnp.float32)}}
    T2_0 = jnp.asarray(T2_np)           # step donates its inputs
    S2_0 = jnp.zeros((F * MRF * 2, 128), jnp.float32)

    p1, s1, l1 = step(params, opt, 0.0, jnp.asarray(idx), jnp.asarray(val),
                      jnp.asarray(lab), jnp.asarray(mask))
    T2o, ggo, lo = jax.jit(_oracle_step)(
        {"T2": T2_0, "w0": jnp.zeros((), jnp.float32)},
        {"T2": {"gg": S2_0}, "w0": {"gg": jnp.zeros((), jnp.float32)}},
        0.0, jnp.asarray(idx), jnp.asarray(val), jnp.asarray(lab),
        jnp.asarray(mask))

    assert abs(float(l1) - float(lo)) < 1e-3 * max(1.0, abs(float(lo)))
    # AdaGrad's first step is sign-unstable where G ~ 0 (summation-order
    # noise); compare weights only where the accumulator is meaningful.
    sig = ggo > 1e-5
    dT = float((jnp.abs(p1["T2"].astype(jnp.float32)
                        - T2o.astype(jnp.float32)) * sig).max())
    rS = float((jnp.abs(s1["T2"]["gg"] - ggo) / (ggo + 1e-2)).max())
    assert dT < 2e-2, f"T2 mismatch {dT}"
    assert rS < 0.2, f"gg mismatch {rS}"


def test_trainer_fit_and_score():
    rng = np.random.default_rng(2)
    t = FFMTrainer(f"-dims {DIMS} -factors {K} -fields {F} -mini_batch {B} "
                   "-opt adagrad -classification -halffloat "
                   "-ffm_table parts -eta0 0.05")
    assert t.layout == "parts" and t.interaction == "fieldmajor"
    # planted signal: label = sign of w-ish feature pattern
    n = 512
    idx = rng.integers(1, DIMS, (n, L)).astype(np.int32)
    lab = np.where(idx[:, 0] % 2 == 0, 1.0, -1.0).astype(np.float32)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
    losses = []
    # 3 epochs, not 6: the planted signal converges fully inside epoch 1
    # (loss ratio ~0.0, acc 1.0 measured) — the extra epochs were ~40s of
    # pure wall against the 870s tier-1 cap on the 2-core container
    for e in range(3):
        for st in range(0, n, B):
            sl = slice(st, st + B)
            batch = SparseBatch(idx[sl], (idx[sl] != 0).astype(np.float32),
                                lab[sl], fld[sl])
            losses.append(float(t._train_batch(t._preprocess_batch(batch))))
    assert losses[-1] < losses[0] * 0.8, losses[:2] + losses[-2:]

    scores = t._score_batch(SparseBatch(
        idx[:64], (idx[:64] != 0).astype(np.float32), lab[:64], fld[:64]))
    assert scores.shape == (64,) and np.isfinite(scores).all()
    # scores orient with labels after training
    acc = ((scores > 0) == (lab[:64] > 0)).mean()
    assert acc > 0.7, acc


def test_partial_batch_padding():
    rng = np.random.default_rng(3)
    t = FFMTrainer(f"-dims {DIMS} -factors {K} -fields {F} -mini_batch {B} "
                   "-opt adagrad -classification -halffloat "
                   "-ffm_table parts")
    idx, val, lab = _mk_batch(rng, b=37)     # not a multiple of 8
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (37, 1))
    b2 = t._preprocess_batch(SparseBatch(idx, val, lab, fld))
    assert b2.batch_size == 128 and b2.n_valid == 37
    lo = float(t._train_batch(b2))
    assert np.isfinite(lo)
    s = t._score_batch(SparseBatch(idx, val, lab, fld))
    assert s.shape == (37,)


def test_model_rows_and_weights_roundtrip():
    rng = np.random.default_rng(4)
    t = FFMTrainer(f"-dims {DIMS} -factors {K} -fields {F} -mini_batch 64 "
                   "-opt adagrad -classification -halffloat "
                   "-ffm_table parts")
    idx, val, lab = _mk_batch(rng, b=64, zero_frac=0.0)
    fld = np.tile(np.arange(L, dtype=np.int32) % F, (64, 1))
    t._train_batch(t._preprocess_batch(SparseBatch(idx, val, lab, fld)))
    t._note_batch(SparseBatch(idx, val, lab, fld))
    rows = list(t.model_rows())
    assert rows[0][0] == "0"                  # w0 row
    assert len(rows) > 1
    w = t._finalized_weights()
    assert w.shape == (F * t.MRF,)
    t._load_weights(np.zeros_like(w))
    assert np.abs(t._finalized_weights()).max() == 0.0


def test_guards():
    with pytest.raises(ValueError, match="adagrad"):
        FFMTrainer(f"-dims {DIMS} -factors {K} -fields {F} -mini_batch 64 "
                   "-opt sgd -classification -halffloat -ffm_table parts")
    t = FFMTrainer(f"-dims {DIMS} -factors {K} -fields {F} -mini_batch 64 "
                   "-opt adagrad -classification -halffloat "
                   "-ffm_table parts")
    # round 4: parts DOES mesh now (make_parts_step_sharded) — but field
    # and batch divisibility are validated (F=31 here; tp=4 cannot divide)
    with pytest.raises(ValueError, match="divisible by the tp axis"):
        t._apply_mesh("dp=2,tp=4")
    with pytest.raises(ValueError, match="MIX"):
        t._get_weights_at(np.array([1, 2], np.int64))


def test_l2_count_lane_matches_slab_oracle():
    """The kernel's count-lane L2 (lam * T[r] * count) must equal the
    joint step's slab-level per-occurrence L2 summed over occurrences."""
    rng = np.random.default_rng(5)
    idx, val, lab = _mk_batch(rng, b=128)
    mask = np.ones(128, np.float32)
    loss = get_loss("logloss")
    interp = jax.default_backend() != "tpu"
    lam_w, lam_v = 0.02, 0.01
    step = fp.make_parts_step(loss, lambda t: 0.1, (0.0, lam_w, lam_v),
                              F, K, MRF, interpret=interp)

    key = jax.random.PRNGKey(7)
    Tl = jnp.concatenate([
        jax.random.normal(key, (F * MRF, F * K)) * 0.1,
        jnp.zeros((F * MRF, 256 - F * K))], axis=1)
    T2_np = np.asarray(Tl.reshape(F * MRF * 2, 128).astype(jnp.bfloat16))
    params = {"T2": jnp.asarray(T2_np), "w0": jnp.zeros((), jnp.float32)}
    opt = {"T2": {"gg": jnp.zeros((F * MRF * 2, 128), jnp.float32)},
           "w0": {"gg": jnp.zeros((), jnp.float32)}}
    p1, s1, _ = step(params, opt, 0.0, jnp.asarray(idx), jnp.asarray(val),
                     jnp.asarray(lab), jnp.asarray(mask))

    # oracle: XLA scatter of (grad + lam*slab*pm), dense AdaGrad
    def oracle(T2, S2):
        wp, hp = 256, 2
        b = idx.shape[0]
        valj = jnp.asarray(val)
        idxT, valT = jnp.asarray(idx).T, valj.T
        fieldT = (jnp.arange(L, dtype=jnp.int32) % F)[:, None]
        rows = fp.parts_row_hash(idxT, fieldT, MRF)
        slab = T2.reshape(F * MRF, hp, 128)[rows]

        def bl(slabf):
            phi = fp._phi_parts(0.0, slabf.reshape(L, b, wp), valT, F, K)
            return (loss.loss(phi, jnp.asarray(lab))).sum()

        gslab = jax.grad(bl)(slab).astype(jnp.bfloat16).astype(
            jnp.float32).reshape(L, b, wp)
        FK = F * K
        pm = (valT != 0).astype(jnp.float32)
        lam_col = jnp.concatenate([
            jnp.full((FK,), lam_v, jnp.float32), jnp.zeros((1,)),
            jnp.zeros((wp - FK - 1,), jnp.float32)])
        lam_col = lam_col.at[FK].set(lam_w)
        gslab = gslab + lam_col * slab.astype(jnp.float32).reshape(
            L, b, wp) * pm[..., None]
        G = jnp.zeros((F * MRF, hp, 128), jnp.float32).at[rows].add(
            gslab.reshape(L, b, hp, 128))
        G2 = G.reshape(F * MRF * hp, 128)
        # pad columns carry no L2 and no grad in the oracle
        gg = S2 + G2 * G2
        T2n = (T2.astype(jnp.float32)
               - 0.1 * G2 / (jnp.sqrt(gg) + 1e-6)).astype(T2.dtype)
        return T2n, gg

    T2o, ggo = jax.jit(oracle)(jnp.asarray(T2_np),
                               jnp.zeros((F * MRF * 2, 128), jnp.float32))
    # compare on live columns only (kernel masks pads; count lane differs)
    wlane = F * K - 128
    live = np.ones((1, 128), np.float32)
    live_odd = (np.arange(128) <= wlane).astype(np.float32)
    live2 = np.stack([live[0], live_odd])
    liveM = jnp.asarray(np.tile(live2, (F * MRF, 1)))
    sig = (ggo > 1e-5) & (liveM > 0)
    dT = float((jnp.abs(p1["T2"].astype(jnp.float32)
                        - T2o.astype(jnp.float32)) * sig).max())
    rS = float(((jnp.abs(s1["T2"]["gg"] - ggo) / (ggo + 1e-2)) * liveM).max())
    assert dT < 2e-2, f"L2 T2 mismatch {dT}"
    assert rS < 0.2, f"L2 gg mismatch {rS}"
