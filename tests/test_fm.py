"""FM/FFM trainers: score-formula correctness vs a naive oracle + convergence
on synthetic interaction data (SURVEY.md §5 golden-convergence style)."""

import numpy as np
import pytest

from hivemall_tpu.frame.evaluation import auc
from hivemall_tpu.io.sparse import SparseDataset
from hivemall_tpu.models.fm import FFMTrainer, FMTrainer


def naive_fm_score(w0, w, V, idx, val):
    """Direct per-row double loop oracle of the FM formula."""
    out = []
    for b in range(idx.shape[0]):
        s = w0 + sum(w[idx[b, l]] * val[b, l] for l in range(idx.shape[1]))
        for i in range(idx.shape[1]):
            for j in range(i + 1, idx.shape[1]):
                s += float(V[idx[b, i]] @ V[idx[b, j]]) * val[b, i] * val[b, j]
        out.append(s)
    return np.asarray(out)


def naive_ffm_score(w0, w, V, idx, val, fld):
    out = []
    for b in range(idx.shape[0]):
        s = w0 + sum(w[idx[b, l]] * val[b, l] for l in range(idx.shape[1]))
        for i in range(idx.shape[1]):
            for j in range(i + 1, idx.shape[1]):
                s += float(V[idx[b, i], fld[b, j]] @ V[idx[b, j], fld[b, i]]
                           ) * val[b, i] * val[b, j]
        out.append(s)
    return np.asarray(out)


def test_fm_score_matches_oracle():
    from hivemall_tpu.ops.fm import fm_score
    rng = np.random.default_rng(0)
    N, K, B, L = 20, 3, 7, 4
    w0 = 0.3
    w = rng.normal(0, 1, N).astype(np.float32)
    V = rng.normal(0, 1, (N, K)).astype(np.float32)
    idx = rng.integers(1, N, (B, L)).astype(np.int32)
    val = rng.uniform(0.5, 1.5, (B, L)).astype(np.float32)
    got = np.asarray(fm_score(np.float32(w0), w, V, idx, val))
    want = naive_fm_score(w0, w, V, idx, val)
    np.testing.assert_allclose(got, want, rtol=2e-4)


def test_ffm_score_matches_oracle():
    from hivemall_tpu.ops.fm import ffm_score
    rng = np.random.default_rng(1)
    N, F, K, B, L = 15, 5, 2, 6, 4
    w0 = -0.2
    w = rng.normal(0, 1, N).astype(np.float32)
    V = rng.normal(0, 1, (N, F, K)).astype(np.float32)
    idx = rng.integers(1, N, (B, L)).astype(np.int32)
    val = rng.uniform(0.5, 1.5, (B, L)).astype(np.float32)
    fld = rng.integers(0, F, (B, L)).astype(np.int32)
    got = np.asarray(ffm_score(np.float32(w0), w, V, idx, val, fld))
    want = naive_ffm_score(w0, w, V, idx, val, fld)
    np.testing.assert_allclose(got, want, rtol=2e-4)


def _xor_dataset(n=2000, seed=0):
    """Pure interaction task: y = +1 iff exactly one of (f1, f2) present —
    linear terms can't solve it, factors must."""
    rng = np.random.default_rng(seed)
    rows, fields, labels = [], [], []
    for _ in range(n):
        a, b = rng.integers(0, 2), rng.integers(0, 2)
        idx = [1 if a else 2, 3 if b else 4]
        rows.append((np.asarray(idx, np.int32), np.ones(2, np.float32)))
        fields.append(np.asarray([0, 1], np.int32))
        labels.append(1.0 if a != b else -1.0)
    return rows, fields, labels


def test_fm_learns_interactions():
    rows, _, labels = _xor_dataset()
    ds = SparseDataset.from_rows(rows, labels)
    t = FMTrainer("-dims 16 -factors 4 -classification -opt adagrad "
                  "-eta fixed -eta0 0.1 -mini_batch 64 -iters 8 -sigma 0.3 "
                  "-lambda0 0 -lambda_w 0 -lambda_v 0")
    t.fit(ds)
    assert auc(np.asarray(labels), t.predict(ds)) > 0.95


def test_ffm_learns_interactions():
    rows, fields, labels = _xor_dataset()
    ds = SparseDataset.from_rows(rows, labels, fields=fields)
    t = FFMTrainer("-dims 16 -factors 4 -fields 4 -classification "
                   "-opt adagrad -eta fixed -eta0 0.1 -mini_batch 64 "
                   "-iters 8 -sigma 0.3 -lambda0 0 -lambda_w 0 -lambda_v 0")
    t.fit(ds)
    assert auc(np.asarray(labels), t.predict(ds)) > 0.95


def test_ffm_udtf_lifecycle_with_string_features():
    t = FFMTrainer("-dims 4096 -factors 2 -fields 8 -classification "
                   "-mini_batch 8 -eta fixed -eta0 0.2 -sigma 0.2")
    rng = np.random.default_rng(3)
    for _ in range(200):
        a, b = rng.integers(0, 2), rng.integers(0, 2)
        feats = [f"0:u{a}:1", f"1:i{b}:1"]     # field:index:value strings
        t.process(feats, 1 if a != b else -1)
    rows = list(t.close())
    assert rows[0][0] == "0"                   # w0 row first
    names = {r[0] for r in rows}
    assert any(n.startswith("u") for n in names)
    assert any(n.startswith("i") for n in names)


def test_fm_regression_targets():
    rng = np.random.default_rng(5)
    rows, labels = [], []
    for _ in range(800):
        i = int(rng.integers(1, 5))
        rows.append((np.asarray([i], np.int32), np.ones(1, np.float32)))
        labels.append(float(i))                # target = feature id
    ds = SparseDataset.from_rows(rows, labels)
    t = FMTrainer("-dims 8 -factors 2 -opt adagrad -eta fixed -eta0 0.5 "
                  "-mini_batch 32 -iters 6 -lambda0 0 -lambda_w 0 -lambda_v 0")
    t.fit(ds)
    pred = t.predict(ds)
    assert np.corrcoef(pred, np.asarray(labels))[0, 1] > 0.98


def test_fm_save_warm_start(tmp_path):
    rows, _, labels = _xor_dataset(300)
    ds = SparseDataset.from_rows(rows, labels)
    a = FMTrainer("-dims 16 -factors 2 -classification -mini_batch 64")
    a.fit(ds)
    p = str(tmp_path / "fm_model.npz")
    a.save_model(p)
    b = FMTrainer(f"-dims 16 -factors 2 -classification -loadmodel {p}")
    np.testing.assert_allclose(a.predict(ds), b.predict(ds), atol=1e-5)


# --- sparse (gather/scatter) step vs dense step ----------------------------

def _factor_step_fixture(kind, opt_name, seed=3):
    import jax.numpy as jnp
    from hivemall_tpu.ops.fm import (_make_factor_step_dense,
                                     _make_factor_step_sparse,
                                     fm_score, ffm_score)
    from hivemall_tpu.ops.losses import get_loss
    from hivemall_tpu.ops.optimizers import make_optimizer

    rng = np.random.default_rng(seed)
    N, F, K, B = 64, 4, 3, 8
    L = 4  # == F so per-row distinct fields keep (idx,field) pairs unique
    loss = get_loss("logloss")
    opt = make_optimizer(opt_name, eta_scheme="fixed", eta0=0.1, reg="no")
    if kind == "ffm":
        V = rng.normal(0, 0.1, (N, F, K)).astype(np.float32)
        score = ffm_score
    else:
        V = rng.normal(0, 0.1, (N, K)).astype(np.float32)
        score = fm_score
    params = {"w0": jnp.zeros(()), "w": jnp.zeros(N), "V": jnp.asarray(V)}
    state = {k: opt.init(np.asarray(v).shape) for k, v in params.items()}
    # duplicate-free indices BATCH-wide (per-occurrence sparse updates only
    # match one dense accumulated update when no id repeats anywhere in the
    # batch), and per-row distinct fields so FFM (idx,field) pairs are unique
    idx = rng.permutation(np.arange(1, N))[:B * L].reshape(B, L).astype(
        np.int32)
    val = rng.uniform(0.5, 1.5, (B, L)).astype(np.float32)
    fld = np.tile(rng.permutation(np.arange(F, dtype=np.int32))[:L], (B, 1))
    lab = (rng.integers(0, 2, B) * 2 - 1).astype(np.float32)
    mask = np.ones(B, np.float32)
    extra = (fld,) if kind == "ffm" else ()
    dense = _make_factor_step_dense(score, loss, opt, (0.0, 0.0, 0.0))
    sparse = _make_factor_step_sparse(kind, loss, opt, (0.0, 0.0, 0.0))
    return params, state, (idx, val, lab, mask), extra, dense, sparse


@pytest.mark.parametrize("kind", ["fm", "ffm"])
@pytest.mark.parametrize("opt_name", ["sgd", "adagrad", "ftrl"])
def test_sparse_step_matches_dense(kind, opt_name):
    """With duplicate-free indices and no L2, the O(batch) gather/scatter step
    must reproduce the O(table) dense step exactly (same math, different
    memory traffic)."""
    import jax
    params, state, (idx, val, lab, mask), extra, dense, sparse = \
        _factor_step_fixture(kind, opt_name)
    copy = jax.tree.map(lambda x: x.copy() if hasattr(x, "copy") else x,
                        (params, state))
    pd, sd, ld = dense(params, state, 0.0, idx, val, lab, mask, *extra)
    ps, ss, ls = sparse(copy[0], copy[1], 0.0, idx, val, lab, mask, *extra)
    np.testing.assert_allclose(float(ld), float(ls), rtol=1e-5)
    if opt_name == "ftrl":
        # FTRL weights live implicitly in (z, n): the dense step eagerly
        # re-materializes the WHOLE table (zeroing untouched random inits),
        # the sparse step is lazy (untouched cells keep their init until
        # first touched — the reference's per-cell behavior). Compare only
        # the entries this batch touched.
        np.testing.assert_allclose(np.asarray(pd["w0"]), np.asarray(ps["w0"]),
                                   rtol=1e-4, atol=1e-6)
        ix = np.asarray(idx).ravel()
        np.testing.assert_allclose(np.asarray(pd["w"])[ix],
                                   np.asarray(ps["w"])[ix],
                                   rtol=1e-4, atol=1e-6)
        if kind == "ffm":
            N, F, K = np.asarray(pd["V"]).shape
            # off-diagonal pairs only: diagonal self-pair cells are
            # deliberately untouched by the sparse step (they never enter
            # the score), while dense FTRL eagerly re-materializes them
            L = np.asarray(idx).shape[1]
            offdiag = ~np.eye(L, dtype=bool)[None].repeat(len(idx), 0)
            flat = (np.asarray(idx)[:, :, None] * F +
                    np.asarray(extra[0])[:, None, :])[offdiag].ravel()
            np.testing.assert_allclose(
                np.asarray(pd["V"]).reshape(N * F, K)[flat],
                np.asarray(ps["V"]).reshape(N * F, K)[flat],
                rtol=1e-4, atol=1e-6)
        else:
            np.testing.assert_allclose(np.asarray(pd["V"])[ix],
                                       np.asarray(ps["V"])[ix],
                                       rtol=1e-4, atol=1e-6)
    else:
        for k in ("w0", "w", "V"):
            np.testing.assert_allclose(np.asarray(pd[k]), np.asarray(ps[k]),
                                       rtol=1e-4, atol=1e-6)


def test_sparse_step_duplicate_indices_accumulate():
    """Duplicate feature ids within a batch must accumulate their gradients
    (scatter-add), not race (last-write-wins)."""
    import jax.numpy as jnp
    from hivemall_tpu.ops.fm import _make_factor_step_sparse
    from hivemall_tpu.ops.losses import get_loss
    from hivemall_tpu.ops.optimizers import make_optimizer

    loss = get_loss("squaredloss")
    opt = make_optimizer("sgd", eta_scheme="fixed", eta0=1.0, reg="no")
    step = _make_factor_step_sparse("fm", loss, opt, (0.0, 0.0, 0.0))
    N, K = 8, 2
    params = {"w0": jnp.zeros(()), "w": jnp.zeros(N),
              "V": jnp.zeros((N, K))}
    state = {k: opt.init(np.asarray(v).shape) for k, v in params.items()}
    # two rows, both touching feature 3 with val 1 → dloss = phi - y = -1 each
    idx = np.array([[3, 0], [3, 0]], np.int32)
    val = np.array([[1.0, 0.0], [1.0, 0.0]], np.float32)
    lab = np.ones(2, np.float32)
    mask = np.ones(2, np.float32)
    p2, _, _ = step(params, state, 0.0, idx, val, lab, mask)
    # squaredloss dloss = (phi - y) = -1 per row; w[3] += eta * 1 * 2 rows
    np.testing.assert_allclose(float(p2["w"][3]), 2.0, rtol=1e-6)


def test_ffm_sparse_convergence_adagrad():
    """FFM with the sparse AdaGrad path learns field-crossed interactions."""
    rng = np.random.default_rng(11)
    n, L, F = 600, 3, 3
    idx = rng.integers(1, 40, (n, L)).astype(np.int32)
    val = np.ones((n, L), np.float32)
    fld = np.tile(np.arange(L, dtype=np.int32), (n, 1))
    y = np.where((idx[:, 0] % 2) == (idx[:, 1] % 2), 1.0, -1.0
                 ).astype(np.float32)
    ds = SparseDataset.from_rows(
        [(idx[i], val[i]) for i in range(n)], y,
        fields=[fld[i] for i in range(n)])
    t = FFMTrainer("-dims 64 -factors 4 -fields 3 -classification "
                   "-mini_batch 64 -iters 30 -opt adagrad -eta0 0.2 -seed 7")
    t.fit(ds)
    assert t.optimizer.sparse_update is not None   # sparse path in use
    scores = t.predict(ds)
    assert auc((y > 0).astype(int), scores) > 0.9


def test_ffm_sparse_no_diagonal_state_pollution():
    """Self-pair cells V[idx_i, field_i] never enter the score (i<j mask);
    the sparse step must not decay them or inflate their AdaGrad state."""
    import jax.numpy as jnp
    from hivemall_tpu.ops.fm import _make_factor_step_sparse
    from hivemall_tpu.ops.losses import get_loss
    from hivemall_tpu.ops.optimizers import make_optimizer

    loss = get_loss("logloss")
    opt = make_optimizer("adagrad", eta_scheme="fixed", eta0=0.1, reg="no")
    step = _make_factor_step_sparse("ffm", loss, opt, (0.01, 0.01, 0.01))
    N, F, K = 16, 2, 2
    rng = np.random.default_rng(0)
    V = jnp.asarray(rng.normal(0, 0.5, (N, F, K)), jnp.float32)
    params = {"w0": jnp.zeros(()), "w": jnp.zeros(N), "V": V.copy()}
    state = {k: opt.init(np.asarray(v).shape) for k, v in params.items()}
    # one row: feature 3 (field 0), feature 7 (field 1); cross pairs touch
    # (3,1) and (7,0); diagonals (3,0)/(7,1) must stay untouched
    idx = np.array([[3, 7]], np.int32)
    val = np.ones((1, 2), np.float32)
    fld = np.array([[0, 1]], np.int32)
    lab = np.ones(1, np.float32)
    mask = np.ones(1, np.float32)
    V0 = np.asarray(V).copy()
    p2, s2, _ = step(params, state, 0.0, idx, val, lab, mask, fld)
    gg = np.asarray(s2["V"]["gg"])
    np.testing.assert_allclose(np.asarray(p2["V"])[3, 0], V0[3, 0])
    np.testing.assert_allclose(np.asarray(p2["V"])[7, 1], V0[7, 1])
    assert gg[3, 0].sum() == 0 and gg[7, 1].sum() == 0
    # the cross cells DID move
    assert np.abs(np.asarray(p2["V"])[3, 1] - V0[3, 1]).sum() > 0
    assert np.abs(np.asarray(p2["V"])[7, 0] - V0[7, 0]).sum() > 0


def test_ffm_sparse_padding_pairs_keep_lazy_init_under_ftrl():
    """Pairs where one side is a padding slot (idx=0/val=0) must not be
    scattered into real (feature, field-0) cells: FTRL's re-materializing
    .set would wipe their lazy init to 0 and freeze the interaction."""
    import jax.numpy as jnp
    from hivemall_tpu.ops.fm import _make_factor_step_sparse
    from hivemall_tpu.ops.losses import get_loss
    from hivemall_tpu.ops.optimizers import make_optimizer

    loss = get_loss("logloss")
    opt = make_optimizer("ftrl")
    step = _make_factor_step_sparse("ffm", loss, opt, (0.0, 0.0, 0.0))
    N, F, K = 16, 3, 2
    rng = np.random.default_rng(2)
    V = jnp.asarray(rng.normal(0, 0.5, (N, F, K)), jnp.float32)
    params = {"w0": jnp.zeros(()), "w": jnp.zeros(N), "V": V.copy()}
    state = {k: opt.init(np.asarray(v).shape) for k, v in params.items()}
    # row: feature 5 (field 1), feature 9 (field 2), one padding slot
    idx = np.array([[5, 9, 0]], np.int32)
    val = np.array([[1.0, 1.0, 0.0]], np.float32)
    fld = np.array([[1, 2, 0]], np.int32)
    V0 = np.asarray(V).copy()
    p2, _, _ = step(params, state, 0.0, idx, val,
                    np.ones(1, np.float32), np.ones(1, np.float32), fld)
    V1 = np.asarray(p2["V"])
    # pair with the padding slot (field 0) must keep its lazy random init
    np.testing.assert_allclose(V1[5, 0], V0[5, 0])
    np.testing.assert_allclose(V1[9, 0], V0[9, 0])
    # the real cross pair (5,f2) x (9,f1) was touched (FTRL materializes)
    assert np.abs(V1[5, 2] - V0[5, 2]).sum() > 0
    assert np.abs(V1[9, 1] - V0[9, 1]).sum() > 0


# --- field-major canonical layout (ops.fm._fused_phi_fieldmajor) -----------

def test_canonicalize_fieldmajor_invariants():
    from hivemall_tpu.io.sparse import canonicalize_fieldmajor
    rng = np.random.default_rng(7)
    F = 5
    for _ in range(10):
        B, L = 4, 11
        idx = rng.integers(1, 999, (B, L)).astype(np.int32)
        val = rng.uniform(0.1, 1, (B, L)).astype(np.float32)
        fld = rng.integers(0, F, (B, L)).astype(np.int32)
        dead = rng.uniform(size=(B, L)) < 0.4
        val[dead] = 0
        idx[dead] = 0
        res = canonicalize_fieldmajor(idx, val, fld, F, max_m=8)
        assert res is not None
        idx2, val2, m = res
        assert idx2.shape == (B, m * F) and (m & (m - 1)) == 0
        for b in range(B):
            orig = sorted((int(i), float(v), int(f)) for i, v, f in
                          zip(idx[b], val[b], fld[b]) if v != 0)
            got = sorted((int(idx2[b, s]), float(val2[b, s]), s % F)
                         for s in range(m * F) if val2[b, s] != 0)
            assert orig == got          # same (feature, value, field) multiset


def test_canonicalize_fieldmajor_overflow_returns_none():
    from hivemall_tpu.io.sparse import canonicalize_fieldmajor
    idx = np.ones((2, 6), np.int32)
    val = np.ones((2, 6), np.float32)
    fld = np.zeros((2, 6), np.int32)       # six features all in field 0
    assert canonicalize_fieldmajor(idx, val, fld, 5, max_m=4) is None
    out = canonicalize_fieldmajor(idx, val, fld, 5, max_m=8)
    assert out is not None and out[2] == 8  # pow2 bucket of m_needed=6


def test_fieldmajor_phi_matches_pairs_phi():
    import jax.numpy as jnp
    from hivemall_tpu.io.sparse import canonicalize_fieldmajor
    from hivemall_tpu.ops.fm import (_fused_phi, _fused_phi_fieldmajor,
                                     ffm_row_hash)
    rng = np.random.default_rng(3)
    F, K, Mr = 5, 3, 1 << 8
    W = F * K + 2
    T = rng.normal(0, 1, (Mr, W)).astype(np.float32)
    for _ in range(5):
        B, L = 6, 9
        idx = rng.integers(1, 1000, (B, L)).astype(np.int32)
        val = rng.uniform(0.1, 1, (B, L)).astype(np.float32)
        fld = rng.integers(0, F, (B, L)).astype(np.int32)
        dead = rng.uniform(size=(B, L)) < 0.3
        val[dead] = 0
        idx[dead] = 0
        idx2, val2, m = canonicalize_fieldmajor(idx, val, fld, F, max_m=8)
        r1 = np.asarray(ffm_row_hash(jnp.asarray(idx), Mr))
        r2 = np.asarray(ffm_row_hash(jnp.asarray(idx2), Mr))
        p1 = np.asarray(_fused_phi(0.3, jnp.asarray(T[r1]), jnp.asarray(val),
                                   jnp.asarray(fld), F, K))
        p2 = np.asarray(_fused_phi_fieldmajor(
            0.3, jnp.asarray(T[r2]), jnp.asarray(val2), F, K))
        # same math, different summation order: f32-noise tolerance
        np.testing.assert_allclose(p1, p2, rtol=2e-3, atol=2e-2)


def test_ffm_fieldmajor_trains_like_pairs():
    """End-to-end: the canonical-batch step and the general pair step are the
    same optimization — same data, same seed => near-identical tables."""
    rows, fields, labels = _xor_dataset(600)
    ds = SparseDataset.from_rows(rows, labels, fields=fields)
    opts = ("-dims 64 -factors 4 -fields 4 -classification -opt adagrad "
            "-eta fixed -eta0 0.1 -mini_batch 64 -iters 4 -sigma 0.3")
    tp = FFMTrainer(opts + " -ffm_interaction pairs")
    tf = FFMTrainer(opts + " -ffm_interaction fieldmajor")
    tp.fit(ds)
    tf.fit(ds)
    assert tf._step_fm is not None and tp._step_fm is None
    Tp = np.asarray(tp.params["T"], np.float32)
    Tf = np.asarray(tf.params["T"], np.float32)
    np.testing.assert_allclose(Tp, Tf, rtol=5e-2, atol=5e-3)
    assert auc(np.asarray(labels), tf.predict(ds)) > 0.95


def test_ffm_auto_interaction_skips_sparse_rows():
    """auto mode must fall back to the pair kernel when rows are sparse
    relative to the field space (canonical width would inflate > 2x)."""
    rng = np.random.default_rng(5)
    rows, fields, labels = [], [], []
    for _ in range(64):
        idx = rng.integers(1, 200, 3).astype(np.int32)
        rows.append((idx, np.ones(3, np.float32)))
        fields.append(rng.integers(0, 64, 3).astype(np.int32))
        labels.append(1.0 if rng.uniform() > 0.5 else -1.0)
    ds = SparseDataset.from_rows(rows, labels, fields=fields)
    t = FFMTrainer("-dims 256 -factors 2 -fields 64 -classification "
                   "-opt adagrad -mini_batch 32")
    b = next(ds.batches(32))
    out = t._preprocess_batch(t._convert_batch(b) if hasattr(
        t, "_convert_batch") else b)
    assert not out.fieldmajor            # 64 fields >> 3-feature rows
    t.fit(ds)                            # trains through the pair path
    assert np.isfinite(t.cumulative_loss)


def test_out_of_range_fields_fold_consistently():
    """Field ids >= F fold mod F in BOTH interaction kernels (parse-path
    normalization) — the fieldmajor and pairs paths must agree on the same
    data (review r2: fieldmajor silently dropped such features)."""
    import jax.numpy as jnp
    from hivemall_tpu.io.sparse import canonicalize_fieldmajor
    from hivemall_tpu.ops.fm import (_fused_phi, _fused_phi_fieldmajor,
                                     ffm_row_hash)
    F, K, Mr = 4, 3, 1 << 8
    W = F * K + 2
    rng = np.random.default_rng(11)
    T = rng.normal(0, 1, (Mr, W)).astype(np.float32)
    idx = np.asarray([[3, 8, 12, 5]], np.int32)
    val = np.ones((1, 4), np.float32)
    fld = np.asarray([[0, 1, 2, 5]], np.int32)       # 5 >= F
    idx2, val2, m = canonicalize_fieldmajor(idx, val, fld, F)
    assert (val2 != 0).sum() == 4                    # nothing dropped
    r1 = np.asarray(ffm_row_hash(jnp.asarray(idx), Mr))
    r2 = np.asarray(ffm_row_hash(jnp.asarray(idx2), Mr))
    p1 = np.asarray(_fused_phi(0.0, jnp.asarray(T[r1]), jnp.asarray(val),
                               jnp.asarray(fld), F, K))
    p2 = np.asarray(_fused_phi_fieldmajor(
        0.0, jnp.asarray(T[r2]), jnp.asarray(val2), F, K))
    np.testing.assert_allclose(p1, p2, rtol=1e-4, atol=1e-4)


def test_ffm_interaction_option_validated_any_layout():
    with pytest.raises(ValueError):
        FFMTrainer("-dims 1000 -fields 4 -ffm_interaction fieldmajro")
    with pytest.raises(ValueError):                 # dense layout, forced fm
        FFMTrainer("-dims 1000 -fields 4 -ffm_interaction fieldmajor")


def test_fm_fused_layout_matches_split():
    """-fm_table fused (one [N,K+pad] row: V|w) is the same optimization as
    the split w/V layout — same data, same seed => matching tables."""
    rows, _, labels = _xor_dataset(600)
    ds = SparseDataset.from_rows(rows, labels)
    opts = ("-dims 64 -factors 4 -classification -opt adagrad -eta fixed "
            "-eta0 0.1 -mini_batch 64 -iters 4 -sigma 0.3")
    # -fm_update occurrence: the split layout's sparse chain is
    # per-occurrence AdaGrad, so the exact-match claim needs the fused
    # layout on the same update shape (minibatch is the throughput default)
    tf = FMTrainer(opts + " -fm_table fused -fm_update occurrence")
    tsp = FMTrainer(opts + " -fm_table split")
    tf.fit(ds)
    tsp.fit(ds)
    assert tf.fm_layout == "fused" and tsp.fm_layout == "split"
    wf, Vf = tf._wv_tables()
    ws, Vs = tsp._wv_tables()
    np.testing.assert_allclose(Vf, Vs, rtol=2e-2, atol=2e-3)
    np.testing.assert_allclose(wf, ws, rtol=2e-2, atol=2e-3)
    np.testing.assert_allclose(tf.predict(ds), tsp.predict(ds),
                               rtol=2e-2, atol=2e-3)
    assert auc(np.asarray(labels), tf.predict(ds)) > 0.95


def test_fm_fused_rejects_dense_only_optimizer():
    with pytest.raises(ValueError):
        FMTrainer("-dims 64 -opt adam -fm_table fused")
    t = FMTrainer("-dims 64 -opt adam")          # auto falls back to split
    assert t.fm_layout == "split"


def test_fm_adareg_increases_lambda_on_overfit():
    """-adareg (SURVEY §3.6 train_fm row): on an overfittable task (tiny
    sample, label noise, ample capacity) held-out loss degrades as the fit
    memorizes -> lambda_w/lambda_v must be adapted UP from their start."""
    rng = np.random.default_rng(0)
    n, d = 120, 512
    rows = [(np.sort(rng.choice(np.arange(1, d), 6, replace=False)).astype(
        np.int32), np.ones(6, np.float32)) for _ in range(n)]
    labels = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)  # pure noise
    ds = SparseDataset.from_rows(rows, labels)
    t = FMTrainer(f"-dims {d} -factors 8 -classification -opt adagrad "
                  "-eta fixed -eta0 0.5 -mini_batch 32 -iters 8 "
                  "-sigma 0.3 -adareg -va_ratio 0.2 "
                  "-lambda_w 0.001 -lambda_v 0.001")
    assert t._adareg
    t.fit(ds)
    # noise labels: validation loss trends worse as training memorizes
    assert t._lams[1] > 0.001 and t._lams[2] > 0.001, t._lams

    # option validation
    with pytest.raises(ValueError, match="va_ratio"):
        FMTrainer("-dims 64 -adareg -va_ratio 0.9")
    with pytest.raises(ValueError, match="adareg"):
        FMTrainer("-dims 64 -opt ftrl -adareg")


def test_fm_adareg_matches_static_when_never_adapted():
    """Epoch 1 runs on the initial lambdas; with -iters 1 the adareg path
    (dynamic-lambda step + holdout) must train the same model the static
    step trains on the same rows."""
    rows, _, labels = _xor_dataset(200)
    ds = SparseDataset.from_rows(rows, labels)
    opts = ("-dims 64 -factors 4 -classification -opt adagrad -eta fixed "
            "-eta0 0.1 -mini_batch 64 -iters 1 -sigma 0.3")
    ta = FMTrainer(opts + " -adareg -va_ratio 0.1")
    ta.fit(ds)
    # same split, same seed: rebuild the training subset and fit static
    rng = np.random.default_rng(42)
    perm = rng.permutation(len(ds))
    n_va = max(1, int(round(len(ds) * 0.1)))
    labels_conv = np.where(np.asarray(labels) > 0, 1.0, -1.0)
    ds_conv = SparseDataset(ds.indices, ds.indptr, ds.values,
                            labels_conv, ds.fields)
    ds_tr = ds_conv.take(perm[n_va:])
    ts = FMTrainer(opts)
    ts._fit_epochs(ds_tr, 1, 64, True, None, None, seed0=42)
    np.testing.assert_allclose(np.asarray(ta.params["T"], np.float32),
                               np.asarray(ts.params["T"], np.float32),
                               rtol=1e-5, atol=1e-6)


def test_fm_minibatch_update_converges_like_occurrence():
    """-fm_update minibatch (one scatter into dense G + dense AdaGrad, the
    FFM fused paths' accumulator semantics) is the adagrad default; it
    must reach the same solution quality as the per-occurrence chain and
    stay close in function space."""
    rows, _, labels = _xor_dataset(600)
    ds = SparseDataset.from_rows(rows, labels)
    opts = ("-dims 64 -factors 4 -classification -opt adagrad -eta fixed "
            "-eta0 0.1 -mini_batch 64 -iters 4 -sigma 0.3")
    tm = FMTrainer(opts)
    assert tm.fm_layout == "fused"
    to = FMTrainer(opts + " -fm_update occurrence")
    tm.fit(ds)
    to.fit(ds)
    y = np.asarray(labels)
    assert auc(y, tm.predict(ds)) > 0.95
    # same optimization problem, mildly different adaptive scaling:
    # predictions agree in rank almost everywhere
    am, ao = tm.predict(ds), to.predict(ds)
    assert np.corrcoef(am, ao)[0, 1] > 0.98
    with pytest.raises(ValueError, match="minibatch"):
        FMTrainer("-dims 64 -opt sgd -fm_update minibatch")


def test_fm_fused_unit_val_elision():
    """Categorical FM batches drop the val array; the fused step rebuilds
    it from idx on device — same model as the explicit-val path."""
    rows, _, labels = _xor_dataset(400)
    ds = SparseDataset.from_rows(rows, labels)
    opts = ("-dims 64 -factors 4 -classification -opt adagrad -eta fixed "
            "-eta0 0.1 -mini_batch 64 -iters 3 -sigma 0.3")
    t1 = FMTrainer(opts)
    b = t1._preprocess_batch(next(ds.batches(64)))
    assert b.val is None                   # elision engaged (all-unit vals)
    t1.fit(ds)
    t2 = FMTrainer(opts)
    t2.UNIT_VAL_ELISION = False
    t2.fit(ds)
    np.testing.assert_allclose(np.asarray(t1.params["T"], np.float32),
                               np.asarray(t2.params["T"], np.float32),
                               rtol=1e-5, atol=1e-6)


def test_fm_warm_start_layout_mismatch_is_friendly(tmp_path):
    """Loading a split-layout save into a fused-layout trainer (or vice
    versa) must raise the diagnostic ValueError, not a raw npz KeyError."""
    t = FMTrainer("-dims 64 -factors 4 -fm_table split -opt adagrad")
    p = str(tmp_path / "m.npz")
    t.save_model(p)
    with pytest.raises(ValueError, match="fm_table"):
        FMTrainer(f"-dims 64 -factors 4 -opt adagrad -loadmodel {p}")


def test_ffm_scoring_fieldmajor_matches_pairs_scorer():
    """decision_function routes canonical batches through the field-major
    scorer; predictions must match the general pairs scorer exactly."""
    rows, fields, labels = _xor_dataset(300)
    ds = SparseDataset.from_rows(rows, labels, fields=fields)
    t = FFMTrainer("-dims 64 -factors 4 -fields 4 -classification "
                   "-opt adagrad -mini_batch 64 -iters 3 -sigma 0.3")
    t.fit(ds)
    fast = t.predict(ds)
    t2 = FFMTrainer("-dims 64 -factors 4 -fields 4 -classification "
                    "-opt adagrad -mini_batch 64 -iters 3 -sigma 0.3 "
                    "-ffm_interaction pairs")
    t2.fit(ds)
    slow = t2.predict(ds)
    np.testing.assert_allclose(fast, slow, rtol=2e-2, atol=2e-3)


def test_ffm_forced_fieldmajor_scoring_falls_back_on_overflow():
    """Forced -ffm_interaction fieldmajor: a scoring row with too many
    same-field features must score through the pairs kernel, not raise."""
    rows, fields, labels = _xor_dataset(100)
    ds = SparseDataset.from_rows(rows, labels, fields=fields)
    t = FFMTrainer("-dims 64 -factors 4 -fields 4 -classification "
                   "-opt adagrad -mini_batch 32 -iters 2 "
                   "-ffm_interaction fieldmajor")
    t.fit(ds)
    # 6 features all in field 0: canonicalization overflows max_m=4
    odd = SparseDataset.from_rows(
        [(np.arange(1, 7, dtype=np.int32), np.ones(6, np.float32))],
        [1.0], fields=[np.zeros(6, np.int32)])
    out = t.predict(odd)
    assert np.isfinite(out).all()


def test_ffm_pack_input_bit_exact():
    """-pack_input on (3-byte idx lanes + f32 label bytes in ONE uint8
    buffer, unpacked on device) must be bit-identical to the unpacked
    path — same params after an epoch, joint layout."""
    import numpy as np
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer

    B, L, F, K, dims, n = 256, 8, 8, 4, 1 << 20, 1024
    rng = np.random.default_rng(1)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32), (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = (f"-dims {dims} -factors {K} -fields {F} -mini_batch {B} "
           f"-opt adagrad -classification -halffloat -seed 5")
    a = FFMTrainer(cfg + " -pack_input off")
    a.fit(ds, epochs=1, shuffle=False, prefetch=False)
    b = FFMTrainer(cfg + " -pack_input on")
    b.fit(ds, epochs=1, shuffle=False, prefetch=False)
    for k2 in a.params:
        pa = np.asarray(a.params[k2], np.float32)
        pb = np.asarray(b.params[k2], np.float32)
        np.testing.assert_array_equal(pa, pb, err_msg=k2)
    assert a.cumulative_loss == b.cumulative_loss


def test_ffm_pack_input_partial_batch_mask():
    """A short tail batch (n_valid < B) must keep its padded rows out of
    the loss on the packed path, matching the unpacked path exactly."""
    import numpy as np
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer

    B, L, F, K, dims, n = 256, 8, 8, 4, 1 << 20, 300   # 300 = 256 + 44
    rng = np.random.default_rng(3)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32), (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = (f"-dims {dims} -factors {K} -fields {F} -mini_batch {B} "
           f"-opt adagrad -classification -halffloat -seed 5")
    a = FFMTrainer(cfg + " -pack_input off")
    a.fit(ds, epochs=1, shuffle=False, prefetch=False)
    b = FFMTrainer(cfg + " -pack_input on")
    b.fit(ds, epochs=1, shuffle=False, prefetch=False)
    for k2 in a.params:
        np.testing.assert_array_equal(np.asarray(a.params[k2], np.float32),
                                      np.asarray(b.params[k2], np.float32),
                                      err_msg=k2)


def test_ffm_device_replay_cache_multi_epoch():
    """-iters/epochs >= 2 with the packed path: epoch 1 streams, later
    epochs replay DEVICE-resident rows. shuffle=False replays the exact
    batch composition, so params must be bit-equal to the uncached path;
    shuffle=True must still converge with the same example count."""
    import numpy as np
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer

    B, L, F, K, dims, n = 256, 8, 8, 4, 1 << 20, 900   # 900 = 3*256 + 132
    rng = np.random.default_rng(11)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32), (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = (f"-dims {dims} -factors {K} -fields {F} -mini_batch {B} "
           "-opt adagrad -classification -halffloat -seed 5 "
           "-pack_input on")
    a = FFMTrainer(cfg)
    a.fit(ds, epochs=3, shuffle=False, prefetch=False)
    b = FFMTrainer(cfg.replace("-pack_input on", "-pack_input off"))
    b.fit(ds, epochs=3, shuffle=False, prefetch=False)
    for k2 in a.params:
        np.testing.assert_array_equal(
            np.asarray(a.params[k2], np.float32),
            np.asarray(b.params[k2], np.float32), err_msg=k2)
    assert a._examples == b._examples == 3 * n
    c = FFMTrainer(cfg)
    c.fit(ds, epochs=3, shuffle=True, prefetch=False)
    assert c._examples == 3 * n
    assert np.isfinite(c.cumulative_loss)


def test_ffm_fit_stream_replay_cache_multi_epoch():
    """fit_stream with an epoch factory: epoch 1 streams + retains the
    staged device buffers, epochs >= 2 replay on device — bit-equal to
    re-streaming the same epochs when replay_shuffle=False (VERDICT r4
    weak #5: the out-of-core path re-paid the link every epoch)."""
    import numpy as np
    from hivemall_tpu.io.sparse import SparseDataset
    from hivemall_tpu.models.fm import FFMTrainer

    B, L, F, K, dims, n = 128, 8, 8, 4, 1 << 20, 520
    rng = np.random.default_rng(12)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32), (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = (f"-dims {dims} -factors {K} -fields {F} -mini_batch {B} "
           "-opt adagrad -classification -halffloat -seed 5 "
           "-pack_input on")

    def factory():
        return ds.batches(B, shuffle=False)

    a = FFMTrainer(cfg)
    a.fit_stream(factory, epochs=3, replay_shuffle=False)
    # uncached reference: identical epochs, streamed each time
    b = FFMTrainer(cfg.replace("-pack_input on", "-pack_input off"))
    for _ in range(3):
        b.fit_stream(factory())
    for k2 in a.params:
        np.testing.assert_array_equal(
            np.asarray(a.params[k2], np.float32),
            np.asarray(b.params[k2], np.float32), err_msg=k2)
    assert a._examples == b._examples == 3 * n

    # iterable + epochs>1 is a usage error; factory with epochs=1 works
    with pytest.raises(ValueError, match="factory"):
        FFMTrainer(cfg).fit_stream(factory(), epochs=2)
    c = FFMTrainer(cfg)
    c.fit_stream(factory, epochs=1)
    assert c._examples == n


def test_step_builders_shared_across_instances():
    """Round 4: jitted steps/scorers are config-cached at module level —
    two same-config trainers share ONE compiled step (the per-instance
    re-jit cost word2vec 4x and LDA 10x before the same fix), while their
    training state stays independent."""
    import numpy as np
    from hivemall_tpu.models.fm import FFMTrainer, FMTrainer

    cfg = ("-dims 4096 -factors 4 -fields 8 -mini_batch 64 -opt adagrad "
           "-classification -halffloat")
    a, b = FFMTrainer(cfg), FFMTrainer(cfg)
    assert a._step_fm_unit is b._step_fm_unit
    assert a._fused_score_fm is b._fused_score_fm
    c = FFMTrainer(cfg + " -lambda_v 0.5")      # different config: distinct
    assert c._step_fm_unit is not a._step_fm_unit
    f1, f2 = FMTrainer("-dims 1024 -factors 4"), FMTrainer("-dims 1024 "
                                                           "-factors 4")
    assert f1._step is f2._step
    # shared step, separate state: training a must not move b
    rng = np.random.default_rng(0)
    rows = [([f"{f}:{int(i)}:1" for f, i in
              zip(range(8), rng.integers(1, 4000, 8))], 1 if k % 2 else -1)
            for k in range(128)]
    for feats, lab in rows:
        a.process(feats, lab)
    list(a.close())
    assert not np.array_equal(np.asarray(a.params["T"], np.float32),
                              np.asarray(b.params["T"], np.float32))


def test_fm_adareg_regression_objective():
    """-adareg with the squared-loss (regression) objective: the holdout
    loss path must work for non-classification FM too."""
    rng = np.random.default_rng(0)
    rows = [(np.sort(rng.choice(np.arange(1, 50), 4,
                                replace=False)).astype(np.int32),
             np.ones(4, np.float32)) for _ in range(120)]
    y = rng.normal(size=120).astype(np.float32)
    t = FMTrainer("-dims 64 -factors 4 -opt adagrad -mini_batch 32 "
                  "-iters 3 -adareg -va_ratio 0.2")
    t.fit(SparseDataset.from_rows(rows, y))
    assert np.isfinite(t._lams).all() and (t._lams > 0).all()


def test_ffm_fit_stream_fail_open_over_budget():
    """fit_stream(epochs>1) with a cache budget the epoch cannot fit:
    replay falls open to re-streaming the factory — same model, same
    example count (no silent data loss)."""
    import numpy as np
    from hivemall_tpu.models.fm import FFMTrainer

    B, L, F, K, dims, n = 128, 8, 8, 4, 1 << 20, 384
    rng = np.random.default_rng(9)
    idx = rng.integers(1, dims, (n, L)).astype(np.int32)
    fld = np.tile(np.arange(L, dtype=np.int32), (n, 1))
    lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
    indptr = np.arange(0, n * L + 1, L, dtype=np.int64)
    ds = SparseDataset(idx.ravel(), indptr, np.ones(n * L, np.float32),
                       lab, fld.ravel())
    cfg = (f"-dims {dims} -factors {K} -fields {F} -mini_batch {B} "
           "-opt adagrad -classification -halffloat -seed 5 "
           "-pack_input on")
    a = FFMTrainer(cfg)
    a._DEVICE_CACHE_MB = 0          # force over-budget -> fail-open
    a.fit_stream(lambda: ds.batches(B, shuffle=False), epochs=3,
                 replay_shuffle=False)
    b = FFMTrainer(cfg)
    for _ in range(3):
        b.fit_stream(ds.batches(B, shuffle=False))
    assert a._examples == b._examples == 3 * n
    np.testing.assert_array_equal(
        np.asarray(a.params["T"], np.float32),
        np.asarray(b.params["T"], np.float32))
