"""FM/FFM trainers: score-formula correctness vs a naive oracle + convergence
on synthetic interaction data (SURVEY.md §5 golden-convergence style)."""

import numpy as np
import pytest

from hivemall_tpu.frame.evaluation import auc
from hivemall_tpu.io.sparse import SparseDataset
from hivemall_tpu.models.fm import FFMTrainer, FMTrainer


def naive_fm_score(w0, w, V, idx, val):
    """Direct per-row double loop oracle of the FM formula."""
    out = []
    for b in range(idx.shape[0]):
        s = w0 + sum(w[idx[b, l]] * val[b, l] for l in range(idx.shape[1]))
        for i in range(idx.shape[1]):
            for j in range(i + 1, idx.shape[1]):
                s += float(V[idx[b, i]] @ V[idx[b, j]]) * val[b, i] * val[b, j]
        out.append(s)
    return np.asarray(out)


def naive_ffm_score(w0, w, V, idx, val, fld):
    out = []
    for b in range(idx.shape[0]):
        s = w0 + sum(w[idx[b, l]] * val[b, l] for l in range(idx.shape[1]))
        for i in range(idx.shape[1]):
            for j in range(i + 1, idx.shape[1]):
                s += float(V[idx[b, i], fld[b, j]] @ V[idx[b, j], fld[b, i]]
                           ) * val[b, i] * val[b, j]
        out.append(s)
    return np.asarray(out)


def test_fm_score_matches_oracle():
    from hivemall_tpu.ops.fm import fm_score
    rng = np.random.default_rng(0)
    N, K, B, L = 20, 3, 7, 4
    w0 = 0.3
    w = rng.normal(0, 1, N).astype(np.float32)
    V = rng.normal(0, 1, (N, K)).astype(np.float32)
    idx = rng.integers(1, N, (B, L)).astype(np.int32)
    val = rng.uniform(0.5, 1.5, (B, L)).astype(np.float32)
    got = np.asarray(fm_score(np.float32(w0), w, V, idx, val))
    want = naive_fm_score(w0, w, V, idx, val)
    np.testing.assert_allclose(got, want, rtol=2e-4)


def test_ffm_score_matches_oracle():
    from hivemall_tpu.ops.fm import ffm_score
    rng = np.random.default_rng(1)
    N, F, K, B, L = 15, 5, 2, 6, 4
    w0 = -0.2
    w = rng.normal(0, 1, N).astype(np.float32)
    V = rng.normal(0, 1, (N, F, K)).astype(np.float32)
    idx = rng.integers(1, N, (B, L)).astype(np.int32)
    val = rng.uniform(0.5, 1.5, (B, L)).astype(np.float32)
    fld = rng.integers(0, F, (B, L)).astype(np.int32)
    got = np.asarray(ffm_score(np.float32(w0), w, V, idx, val, fld))
    want = naive_ffm_score(w0, w, V, idx, val, fld)
    np.testing.assert_allclose(got, want, rtol=2e-4)


def _xor_dataset(n=2000, seed=0):
    """Pure interaction task: y = +1 iff exactly one of (f1, f2) present —
    linear terms can't solve it, factors must."""
    rng = np.random.default_rng(seed)
    rows, fields, labels = [], [], []
    for _ in range(n):
        a, b = rng.integers(0, 2), rng.integers(0, 2)
        idx = [1 if a else 2, 3 if b else 4]
        rows.append((np.asarray(idx, np.int32), np.ones(2, np.float32)))
        fields.append(np.asarray([0, 1], np.int32))
        labels.append(1.0 if a != b else -1.0)
    return rows, fields, labels


def test_fm_learns_interactions():
    rows, _, labels = _xor_dataset()
    ds = SparseDataset.from_rows(rows, labels)
    t = FMTrainer("-dims 16 -factors 4 -classification -opt adagrad "
                  "-eta fixed -eta0 0.1 -mini_batch 64 -iters 8 -sigma 0.3 "
                  "-lambda0 0 -lambda_w 0 -lambda_v 0")
    t.fit(ds)
    assert auc(np.asarray(labels), t.predict(ds)) > 0.95


def test_ffm_learns_interactions():
    rows, fields, labels = _xor_dataset()
    ds = SparseDataset.from_rows(rows, labels, fields=fields)
    t = FFMTrainer("-dims 16 -factors 4 -fields 4 -classification "
                   "-opt adagrad -eta fixed -eta0 0.1 -mini_batch 64 "
                   "-iters 8 -sigma 0.3 -lambda0 0 -lambda_w 0 -lambda_v 0")
    t.fit(ds)
    assert auc(np.asarray(labels), t.predict(ds)) > 0.95


def test_ffm_udtf_lifecycle_with_string_features():
    t = FFMTrainer("-dims 4096 -factors 2 -fields 8 -classification "
                   "-mini_batch 8 -eta fixed -eta0 0.2 -sigma 0.2")
    rng = np.random.default_rng(3)
    for _ in range(200):
        a, b = rng.integers(0, 2), rng.integers(0, 2)
        feats = [f"0:u{a}:1", f"1:i{b}:1"]     # field:index:value strings
        t.process(feats, 1 if a != b else -1)
    rows = list(t.close())
    assert rows[0][0] == "0"                   # w0 row first
    names = {r[0] for r in rows}
    assert any(n.startswith("u") for n in names)
    assert any(n.startswith("i") for n in names)


def test_fm_regression_targets():
    rng = np.random.default_rng(5)
    rows, labels = [], []
    for _ in range(800):
        i = int(rng.integers(1, 5))
        rows.append((np.asarray([i], np.int32), np.ones(1, np.float32)))
        labels.append(float(i))                # target = feature id
    ds = SparseDataset.from_rows(rows, labels)
    t = FMTrainer("-dims 8 -factors 2 -opt adagrad -eta fixed -eta0 0.5 "
                  "-mini_batch 32 -iters 6 -lambda0 0 -lambda_w 0 -lambda_v 0")
    t.fit(ds)
    pred = t.predict(ds)
    assert np.corrcoef(pred, np.asarray(labels))[0, 1] > 0.98


def test_fm_save_warm_start(tmp_path):
    rows, _, labels = _xor_dataset(300)
    ds = SparseDataset.from_rows(rows, labels)
    a = FMTrainer("-dims 16 -factors 2 -classification -mini_batch 64")
    a.fit(ds)
    p = str(tmp_path / "fm_model.npz")
    a.save_model(p)
    b = FMTrainer(f"-dims 16 -factors 2 -classification -loadmodel {p}")
    np.testing.assert_allclose(a.predict(ds), b.predict(ds), atol=1e-5)
