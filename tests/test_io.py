import numpy as np

from hivemall_tpu.io import (ReplayCache, SparseDataset, amplify, rand_amplify,
                             read_libsvm, write_libsvm)
from hivemall_tpu.io.libsvm import synthetic_classification
from hivemall_tpu.io.sparse import parse_feature_strings


def small_ds():
    rows = [(np.array([1, 5]), np.array([1.0, 2.0])),
            (np.array([2]), np.array([0.5])),
            (np.array([1, 2, 3]), np.array([1., 1., 1.]))]
    return SparseDataset.from_rows(rows, [1.0, -1.0, 1.0])


def test_roundtrip_libsvm(tmp_path):
    ds = small_ds()
    p = str(tmp_path / "t.libsvm")
    write_libsvm(ds, p)
    ds2 = read_libsvm(p)
    assert np.array_equal(ds.indices, ds2.indices)
    assert np.array_equal(ds.indptr, ds2.indptr)
    assert np.allclose(ds.values, ds2.values)
    assert np.allclose(ds.labels, ds2.labels)


def test_batches_padding():
    ds = small_ds()
    batches = list(ds.batches(2))
    assert len(batches) == 2
    b0 = batches[0]
    assert b0.idx.shape == (2, 3)
    assert b0.idx[1, 1] == 0 and b0.val[1, 1] == 0.0   # padding
    b1 = batches[1]
    assert b1.n_valid == 1
    assert b1.row_mask.tolist() == [1.0, 0.0]


def test_batches_shuffle_covers_all():
    ds, _ = synthetic_classification(100, 50, seed=1)
    seen = []
    for b in ds.batches(32, shuffle=True, seed=7):
        nv = b.n_valid or b.batch_size
        seen.extend(b.label[:nv].tolist())
    assert len(seen) == 100


def test_amplify():
    ds = small_ds()
    a = amplify(ds, 3)
    assert len(a) == 9
    # reference AmplifierUDTF order: each row emitted xtimes consecutively
    assert np.allclose(a.labels, np.repeat(ds.labels, 3))
    r0, r1 = a.row(0), a.row(1)
    assert np.array_equal(r0[0], r1[0])
    assert np.array_equal(a.row(3)[0], ds.row(1)[0])


def test_rand_amplify_preserves_multiset():
    ds = small_ds()
    a = rand_amplify(ds, 2, bufsize=4, seed=0)
    assert len(a) == 6
    assert sorted(a.labels.tolist()) == sorted((ds.labels.tolist() * 2))


def test_replay_cache():
    ds = small_ds()
    cache = ReplayCache()
    batches = list(cache.epochs(ds, iters=3, batch_size=2, shuffle=True))
    total = sum((b.n_valid or b.batch_size) for b in batches)
    assert total == 9


def test_parse_feature_strings():
    idx, val = parse_feature_strings(["1:0.5", "7", "0:1.0"])
    assert idx.tolist() == [1, 7, 0]
    assert np.allclose(val, [0.5, 1.0, 1.0])
    # hashed string features land in [1, 2^24]
    idx2, val2 = parse_feature_strings(["height:1.7", "cat#tokyo"])
    assert (idx2 >= 1).all()
    assert np.allclose(val2, [1.7, 1.0])


def test_synthetic_separable():
    ds, w = synthetic_classification(200, 30, seed=3)
    assert len(ds) == 200
    assert set(np.unique(ds.labels)) <= {-1.0, 1.0}


def test_read_libsvm_ffm_triples(tmp_path):
    """libffm-style field:index:value ingest (ffm_features output format)."""
    from hivemall_tpu.io.libsvm import read_libsvm
    p = tmp_path / "ffm.libsvm"
    p.write_text("1 0:3:1 1:7:0.5\n-1 cat:5:2 1:9\n")
    ds = read_libsvm(str(p), ffm=True, num_fields=4)
    assert ds.fields is not None
    assert list(ds.indices) == [3, 7, 5, 9]
    assert list(ds.values) == [1.0, 0.5, 2.0, 1.0]
    assert list(ds.fields[:2]) == [0, 1]
    assert 0 <= int(ds.fields[2]) < 4        # hashed string field name
    assert int(ds.fields[3]) == 1
    import pytest
    bad = tmp_path / "bad.libsvm"
    bad.write_text("1 justindex\n")
    with pytest.raises(ValueError):
        read_libsvm(str(bad), ffm=True, num_fields=4)
