"""tokenize_ja dictionary-lattice segmenter test vectors (VERDICT r1
missing #4 / SURVEY.md §3.19). Expected segmentations follow Kuromoji's
standard-mode output on these classic phrases."""

from hivemall_tpu.frame.ja_segmenter import LEXICON, segment
from hivemall_tpu.frame.nlp import set_ja_tokenizer, tokenize_ja

VECTORS = [
    # the classic all-hiragana garden path — impossible for script
    # heuristics, requires the dictionary lattice
    ("すもももももももものうち",
     ["すもも", "も", "もも", "も", "もも", "の", "うち"]),
    ("私の名前は中野です", ["私", "の", "名前", "は", "中野", "です"]),
    ("吾輩は猫である", ["吾輩", "は", "猫", "で", "ある"]),
    ("学校に行きました", ["学校", "に", "行き", "まし", "た"]),
    ("東京都に住んでいます",
     ["東京", "都", "に", "住ん", "で", "い", "ます"]),
    ("これはテストです", ["これ", "は", "テスト", "です"]),
    ("コンピュータを使って日本語を勉強します",
     ["コンピュータ", "を", "使っ", "て", "日本語", "を", "勉強",
      "し", "ます"]),
]


def test_segment_vectors():
    for text, expect in VECTORS:
        assert segment(text) == expect, (text, segment(text))


def test_tokenize_ja_uses_segmenter():
    assert tokenize_ja("私の名前は中野です") == \
        ["私", "の", "名前", "は", "中野", "です"]


def test_tokenize_ja_stopwords():
    toks = tokenize_ja("私の名前は中野です", stopwords=["の", "は", "です"])
    assert toks == ["私", "名前", "中野"]


def test_punctuation_and_ascii():
    assert segment("Hello、世界！") == ["Hello", "世界"]
    assert segment("TPUで2024年に") == ["TPU", "で", "2024", "年", "に"]


def test_override_hook_still_wins():
    set_ja_tokenizer(lambda t: ["X"])
    try:
        assert tokenize_ja("なんでも") == ["X"]
    finally:
        set_ja_tokenizer(None)


def test_lexicon_sanity():
    # particles stay cheapest so the lattice prefers splitting them off
    assert all(LEXICON[p] <= 300 for p in ("は", "が", "の", "を"))
    assert len(LEXICON) > 300


# --- Chinese dictionary segmenter (tokenize_cn backend) ---------------------

def test_cn_segment_recovers_dictionary_words():
    from hivemall_tpu.frame.cn_segmenter import segment
    assert segment("我们在北京学习中文") == ["我们", "在", "北京", "学习", "中文"]
    assert segment("他喜欢吃苹果") == ["他", "喜欢", "吃", "苹果"]
    assert segment("图书馆里有很多书") == ["图书馆", "里", "有", "很多", "书"]


def test_cn_segment_mixed_scripts_and_oov():
    from hivemall_tpu.frame.cn_segmenter import segment
    toks = segment("我用Python3写程序")
    assert "Python3" in toks and "程序" in toks and "我" in toks
    # OOV han falls back to single characters, nothing is dropped
    assert "".join(t for t in segment("鑫森淼焱垚") ) == "鑫森淼焱垚"


def test_tokenize_cn_stopwords_and_override():
    from hivemall_tpu.frame.nlp import tokenize_cn, set_cn_tokenizer
    assert "的" not in tokenize_cn("我的书", stopwords=["的"])
    set_cn_tokenizer(lambda s: ["X"])
    try:
        assert tokenize_cn("我的书") == ["X"]
    finally:
        set_cn_tokenizer(None)


def test_ipadic_csv_loader_roundtrip(tmp_path):
    """IPADIC-format CSV drop-in (round 4): load a fragment in the
    mecab-ipadic layout, verify the new words win in segmentation and the
    POS-mapped classes register; vendored behavior is untouched for text
    not involving the new entries."""
    import importlib
    from hivemall_tpu.frame import ja_segmenter as js

    before = js.segment("すもももももももものうち")
    # two made-up-but-well-formed dictionary words the vendored lexicon
    # cannot know, in IPADIC column layout: surface,lid,rid,wcost,POS1,...
    csv = tmp_path / "noun.csv"
    csv.write_text(
        "電脳空間,1285,1285,4000,名詞,一般,*,*,*,*,電脳空間,デンノウクウカン,デンノークーカン\n"
        "超電磁砲,1285,1285,4500,名詞,固有名詞,*,*,*,*,超電磁砲,チョウデンジホウ,チョーデンジホー\n"
        "ゆえ,305,305,3000,助詞,接続助詞,*,*,*,*,ゆえ,ユエ,ユエ\n",
        encoding="utf-8")
    try:
        n = js.load_ipadic_csv(str(csv))
        assert n == 3
        assert "電脳空間" in js.LEXICON and "ゆえ" in js._PARTICLE_SET
        got = js.segment("電脳空間の超電磁砲")
        assert got == ["電脳空間", "の", "超電磁砲"], got
        # cost mapping: common (low wcost) < rare (high wcost)
        assert js.LEXICON["電脳空間"] < js.LEXICON["超電磁砲"]
        # vendored behavior unchanged
        assert js.segment("すもももももももものうち") == before
    finally:
        importlib.reload(js)      # restore the vendored lexicon for other
        # tests (module-level state was mutated by the loader)


def test_paradigm_lexicon_scale_and_forms():
    """The generated lexicon (frame.ja_lexicon) expands seed paradigms to
    thousands of real surface forms and they resolve in the lattice."""
    from hivemall_tpu.frame.ja_lexicon import (expand_godan, expand_ichidan,
                                               expand_i_adjective,
                                               generated_entries)
    from hivemall_tpu.frame.ja_segmenter import LEXICON, segment

    assert expand_godan("書く") == ["書く", "書き", "書い", "書か", "書け",
                                    "書こ"]
    assert expand_godan("読む") == ["読む", "読み", "読ん", "読ま", "読め",
                                    "読も"]
    assert expand_ichidan("食べる") == ["食べる", "食べ"]
    assert expand_i_adjective("高い") == ["高い", "高く", "高かっ",
                                          "高けれ"]
    g = generated_entries()
    assert len(g) > 3500, len(g)
    assert len(LEXICON) > 3800, len(LEXICON)
    # paradigm forms segment: potential stem + auxiliary chain
    assert segment("漢字が読めます") == ["漢字", "が", "読め", "ます"] or \
        segment("漢字が読めます")[-2:] == ["読め", "ます"]

