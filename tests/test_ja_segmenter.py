"""tokenize_ja dictionary-lattice segmenter test vectors (VERDICT r1
missing #4 / SURVEY.md §3.19). Expected segmentations follow Kuromoji's
standard-mode output on these classic phrases."""

from hivemall_tpu.frame.ja_segmenter import LEXICON, segment
from hivemall_tpu.frame.nlp import set_ja_tokenizer, tokenize_ja

VECTORS = [
    # the classic all-hiragana garden path — impossible for script
    # heuristics, requires the dictionary lattice
    ("すもももももももものうち",
     ["すもも", "も", "もも", "も", "もも", "の", "うち"]),
    ("私の名前は中野です", ["私", "の", "名前", "は", "中野", "です"]),
    ("吾輩は猫である", ["吾輩", "は", "猫", "で", "ある"]),
    ("学校に行きました", ["学校", "に", "行き", "まし", "た"]),
    ("東京都に住んでいます",
     ["東京", "都", "に", "住ん", "で", "い", "ます"]),
    ("これはテストです", ["これ", "は", "テスト", "です"]),
    ("コンピュータを使って日本語を勉強します",
     ["コンピュータ", "を", "使っ", "て", "日本語", "を", "勉強",
      "し", "ます"]),
]


def test_segment_vectors():
    for text, expect in VECTORS:
        assert segment(text) == expect, (text, segment(text))


def test_tokenize_ja_uses_segmenter():
    assert tokenize_ja("私の名前は中野です") == \
        ["私", "の", "名前", "は", "中野", "です"]


def test_tokenize_ja_stopwords():
    toks = tokenize_ja("私の名前は中野です", stopwords=["の", "は", "です"])
    assert toks == ["私", "名前", "中野"]


def test_punctuation_and_ascii():
    assert segment("Hello、世界！") == ["Hello", "世界"]
    assert segment("TPUで2024年に") == ["TPU", "で", "2024", "年", "に"]


def test_override_hook_still_wins():
    set_ja_tokenizer(lambda t: ["X"])
    try:
        assert tokenize_ja("なんでも") == ["X"]
    finally:
        set_ja_tokenizer(None)


def test_lexicon_sanity():
    # particles stay cheapest so the lattice prefers splitting them off
    assert all(LEXICON[p] <= 300 for p in ("は", "が", "の", "を"))
    assert len(LEXICON) > 300


# --- Chinese dictionary segmenter (tokenize_cn backend) ---------------------

def test_cn_segment_recovers_dictionary_words():
    from hivemall_tpu.frame.cn_segmenter import segment
    assert segment("我们在北京学习中文") == ["我们", "在", "北京", "学习", "中文"]
    assert segment("他喜欢吃苹果") == ["他", "喜欢", "吃", "苹果"]
    assert segment("图书馆里有很多书") == ["图书馆", "里", "有", "很多", "书"]


def test_cn_segment_mixed_scripts_and_oov():
    from hivemall_tpu.frame.cn_segmenter import segment
    toks = segment("我用Python3写程序")
    assert "Python3" in toks and "程序" in toks and "我" in toks
    # OOV han falls back to single characters, nothing is dropped
    assert "".join(t for t in segment("鑫森淼焱垚") ) == "鑫森淼焱垚"


def test_tokenize_cn_stopwords_and_override():
    from hivemall_tpu.frame.nlp import tokenize_cn, set_cn_tokenizer
    assert "的" not in tokenize_cn("我的书", stopwords=["的"])
    set_cn_tokenizer(lambda s: ["X"])
    try:
        assert tokenize_cn("我的书") == ["X"]
    finally:
        set_cn_tokenizer(None)
